"""Tests for the fused Pallas render kernel (interpret mode on the CPU mesh).

The oracle is ``reference_render`` — the XLA gather path with the kernel's
pixel-space contract — which is itself pinned against the public
``render_mpi`` API (and transitively against the torch oracle by the
existing render parity suite).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_vision_tpu.core import render
from mpi_vision_tpu.core.camera import inv_depths
from mpi_vision_tpu.core.sampling import Convention
from mpi_vision_tpu.kernels import render_pallas as rp


def _mpi(rng, p, h, w):
  return jnp.asarray(rng.uniform(0, 1, (p, 4, h, w)).astype(np.float32))


def _intrinsics(h, w):
  return jnp.asarray(
      np.array([[0.6 * w, 0, w / 2], [0, 0.6 * w, h / 2], [0, 0, 1]],
               np.float32))[None]


def _pose(tx=0.0, ty=0.0, tz=0.0, rx=0.0, ry=0.0):
  pose = np.eye(4, dtype=np.float32)
  cx, sx = np.cos(rx), np.sin(rx)
  cy, sy = np.cos(ry), np.sin(ry)
  rot_x = np.array([[1, 0, 0], [0, cx, -sx], [0, sx, cx]], np.float32)
  rot_y = np.array([[cy, 0, sy], [0, 1, 0], [-sy, 0, cy]], np.float32)
  pose[:3, :3] = rot_y @ rot_x
  pose[:3, 3] = [tx, ty, tz]
  return jnp.asarray(pose)[None]


TRANSLATION = dict(tx=0.06, ty=-0.03, tz=-0.04)
ROTATION = dict(tx=0.04, ty=0.02, tz=0.03, rx=0.006, ry=-0.008)


class TestPixelHomographies:

  @pytest.mark.parametrize("convention", list(Convention))
  def test_matches_public_render_path(self, rng, convention):
    """reference_render(pixel homs) == render_mpi for every convention."""
    p, h, w = 4, 24, 256
    planes = _mpi(rng, p, h, w)
    depths = inv_depths(1.0, 100.0, p)
    pose, k = _pose(**ROTATION), _intrinsics(h, w)
    homs = rp.pixel_homographies(pose, depths, k, h, w, convention)
    got = rp.reference_render(planes, homs[:, 0])
    want = render.render_mpi(
        jnp.moveaxis(planes, 1, -1)[:, None], pose, depths, k,
        convention=convention, method="scan", planes_leading=True)[0]
    # EXACT folds to the identity (bit-equal coords); the REF conventions
    # fold the rescale into the 3x3, which reassociates float ops and can
    # move a tap coordinate by ~1e-3 px — well inside the 1e-3 parity budget.
    atol = 1e-5 if convention is Convention.EXACT else 2e-3
    np.testing.assert_allclose(
        np.moveaxis(np.asarray(got), 0, -1), np.asarray(want),
        atol=atol, rtol=0)

  def test_separable_detection(self):
    depths = inv_depths(1.0, 100.0, 3)
    k = _intrinsics(32, 256)
    assert rp.is_separable(
        rp.pixel_homographies(_pose(**TRANSLATION), depths, k, 32, 256))
    assert not rp.is_separable(
        rp.pixel_homographies(_pose(**ROTATION), depths, k, 32, 256))


class TestFusedKernel:

  @pytest.mark.parametrize("separable,pose_kw", [
      (False, ROTATION),
      (False, TRANSLATION),
      (True, TRANSLATION),
  ])
  def test_parity_vs_reference(self, rng, separable, pose_kw):
    p, h, w = 5, 32, 256
    planes = _mpi(rng, p, h, w)
    depths = inv_depths(1.0, 100.0, p)
    homs = rp.pixel_homographies(
        _pose(**pose_kw), depths, _intrinsics(h, w), h, w)[:, 0]
    got = rp.render_mpi_fused(planes, homs, separable)
    want = rp.reference_render(planes, homs)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=1e-4, rtol=0)

  def test_identity_pose_is_identity_composite(self, rng):
    p, h, w = 3, 24, 256
    planes = _mpi(rng, p, h, w)
    depths = inv_depths(1.0, 100.0, p)
    homs = rp.pixel_homographies(
        _pose(), depths, _intrinsics(h, w), h, w)[:, 0]
    got = rp.render_mpi_fused(planes, homs, True)
    want = rp.reference_render(planes, homs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)

  def test_zeros_padding_offscreen(self, rng):
    """A large shift leaves out-of-image regions exactly black."""
    p, h, w = 2, 24, 256
    planes = jnp.ones((p, 4, h, w), jnp.float32)
    depths = inv_depths(1.0, 100.0, p)
    # Big sideways translation: part of the target view sees off-image.
    homs = rp.pixel_homographies(
        _pose(tx=1.2), depths, _intrinsics(h, w), h, w)[:, 0]
    got = np.asarray(rp.render_mpi_fused(planes, homs, True))
    want = np.asarray(rp.reference_render(planes, homs))
    np.testing.assert_allclose(got, want, atol=1e-4, rtol=0)
    assert (got == 0).any(), "expected some exactly-zero off-image pixels"

  def test_non_square(self, rng):
    p, h, w = 3, 40, 384
    planes = _mpi(rng, p, h, w)
    depths = inv_depths(1.0, 100.0, p)
    homs = rp.pixel_homographies(
        _pose(**ROTATION), depths, _intrinsics(h, w), h, w)[:, 0]
    np.testing.assert_allclose(
        np.asarray(rp.render_mpi_fused(planes, homs)),
        np.asarray(rp.reference_render(planes, homs)), atol=1e-4, rtol=0)

  @pytest.mark.parametrize("hw", [(30, 200), (25, 300), (16, 640)])
  def test_untiled_shapes_auto_pad(self, rng, hw):
    """Arbitrary sizes auto-pad to the tile geometry and crop back —
    exact under zeros-padding semantics (utils.py:174), so e.g. the 224^2
    training scale can use the fused path."""
    h, w = hw
    p = 2
    planes = _mpi(rng, p, h, w)
    depths = inv_depths(1.0, 100.0, p)
    homs = rp.pixel_homographies(
        _pose(**ROTATION), depths, _intrinsics(h, w), h, w)[:, 0]
    got = rp.render_mpi_fused(planes, homs, separable=False)
    assert got.shape == (3, h, w)
    want = rp.reference_render(planes, homs)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-4, rtol=0)

  def test_separable_wide_scale_window_coverage(self, rng):
    """Horizontal scale 1.3 with worst-case window alignment (regression).

    Window bases align down from the leftmost tap, so a chunk whose x_lo
    lands high in its 128-block needs the third gather window; with only
    two windows this produced ~1.0 max error (dropped taps)."""
    p, h, w = 3, 24, 640
    planes = _mpi(rng, p, h, w)
    # u = 1.3*ox + 55: chunk 1's x_lo = 221 (mod 128 = 93), taps reach 387,
    # past the two-window coverage end 384.
    hom = np.array([[1.3, 0, 55.0], [0, 1, 3.0], [0, 0, 1]], np.float32)
    homs = jnp.asarray(np.broadcast_to(hom, (p, 3, 3)))
    assert rp.fits_envelope(homs, h, w, separable=True)
    got = rp.render_mpi_fused(planes, homs, separable=True, check=False)
    want = rp.reference_render(planes, homs)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=1e-4, rtol=0)

  def test_general_wide_scale_falls_back(self, rng):
    """Horizontal scale 2.5 exceeds the shared kernel's window coverage.

    A chunk's taps span ~320 source columns; with worst-case 128-alignment
    the 3-window union cannot cover them, so the plan must reject and the
    checked call must return exact XLA output instead of dropping taps."""
    p, h, w = 2, 24, 768
    planes = _mpi(rng, p, h, w)
    hom = np.array([[2.5, 0.01, 10.0], [0.01, 1, 2.0], [0, 0, 1]], np.float32)
    homs = jnp.asarray(np.broadcast_to(hom, (p, 3, 3)))
    assert rp._plan_shared(homs, h, w) is None
    assert not rp.fits_envelope(homs, h, w, separable=False)
    got = rp.render_mpi_fused(planes, homs, separable=False)
    want = rp.reference_render(planes, homs)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=1e-4, rtol=0)

  def test_out_of_envelope_falls_back_to_reference(self, rng):
    """Eager calls outside the coverage envelope return exact XLA output."""
    p, h, w = 2, 24, 768
    planes = _mpi(rng, p, h, w)
    # Horizontal scale 4: chunk 0's in-image taps reach column 508, beyond
    # its three-window coverage end 384 (and the general path's four-window
    # guarantee is exceeded for interior chunks at this scale too).
    hom = np.array([[4.0, 0, 0.0], [0, 1, 0.0], [0, 0, 1]], np.float32)
    homs = jnp.asarray(np.broadcast_to(hom, (p, 3, 3)))
    assert not rp.fits_envelope(homs, h, w)
    got = rp.render_mpi_fused(planes, homs, separable=True)
    want = rp.reference_render(planes, homs)
    # Fallback output comes from the jitted reference; XLA fusion on CPU
    # reassociates float ops vs the eager oracle (<= ~5e-5, budget 1e-3).
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=1e-4, rtol=0)

  def test_boundary_tap_row_rejected(self, rng):
    """Rows mapping to v in (H-1, H) still tap source row H-1 (regression).

    A pose whose strip band sits low while one row reaches v = H-0.5 must
    never be rendered with the 0.5-weight H-1 tap silently dropped: the
    SHARED planner must reject it (its band misses the tap). The banded
    middle tier now covers this pose — with the boundary tap in-slice —
    so the checked render goes banded and must match the oracle exactly."""
    p, h, w = 2, 48, 128
    planes = _mpi(rng, p, h, w)
    hom = np.array([[0.1, 0, 10.0], [0, -13.3, 653.6], [0, -1, 47.6]],
                   np.float32)
    homs = jnp.asarray(np.broadcast_to(hom, (p, 3, 3)))
    assert rp._plan_shared(homs, h, w) is None
    assert rp._plan_banded(np.asarray(homs), h, w) is not None
    got = rp.render_mpi_fused(planes, homs, separable=False)
    want = rp.reference_render(planes, homs)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=1e-4, rtol=0)

  def test_fits_envelope_accepts_normal_poses(self, rng):
    p, h, w = 4, 32, 256
    depths = inv_depths(1.0, 100.0, p)
    for kw in (TRANSLATION, ROTATION):
      homs = rp.pixel_homographies(
          _pose(**kw), depths, _intrinsics(h, w), h, w)[:, 0]
      assert rp.fits_envelope(homs, h, w)

  def test_gradients_flow_through_vjp(self, rng):
    p, h, w = 3, 24, 256
    planes = _mpi(rng, p, h, w)
    depths = inv_depths(1.0, 100.0, p)
    homs = rp.pixel_homographies(
        _pose(**TRANSLATION), depths, _intrinsics(h, w), h, w)[:, 0]

    g_fused = jax.grad(lambda x: rp.render_mpi_fused(x, homs).sum())(planes)
    g_ref = jax.grad(lambda x: rp.reference_render(x, homs).sum())(planes)
    np.testing.assert_allclose(
        np.asarray(g_fused), np.asarray(g_ref), atol=1e-4, rtol=0)


class TestSharedKernel:
  """The shared-gather general path: rotations, tiled 2-D output blocks."""

  @pytest.mark.parametrize("pose_kw,hw", [
      (ROTATION, (48, 384)),
      (dict(rx=0.03, ry=0.03, tx=0.05), (48, 384)),     # ~1.7 deg rotation
      (dict(rx=-0.02, ry=0.035, tz=-0.04), (40, 768)),  # two tiles wide
      (dict(ry=0.0175), (64, 384)),                     # pure 1-deg yaw pan
      (TRANSLATION, (32, 256)),
  ])
  def test_parity_vs_reference(self, rng, pose_kw, hw):
    h, w = hw
    p = 3
    planes = _mpi(rng, p, h, w)
    depths = inv_depths(1.0, 100.0, p)
    homs = rp.pixel_homographies(
        _pose(**pose_kw), depths, _intrinsics(h, w), h, w)[:, 0]
    plan = rp._plan_shared(homs, h, w)
    assert plan is not None
    got = rp._SHARED[plan](planes[None], homs[None])[0]
    want = rp.reference_render(planes, homs)
    # f32 tap coordinates can round across a bilinear boundary differently
    # than the oracle's float path on isolated pixels (<= ~2e-4 on a unit-
    # range image; parity budget is 1e-3).
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-4, rtol=0)

  def test_yaw_pan_uses_two_tap_fan(self):
    """A pure yaw pan has h01 = h21 = 0: u is row-independent, so the
    strip-shared tap fan needs only the 2 bilinear taps."""
    h, w = 64, 384
    depths = inv_depths(1.0, 100.0, 3)
    homs = rp.pixel_homographies(
        _pose(ry=0.0175), depths, _intrinsics(h, w), h, w)[:, 0]
    plan = rp._plan_shared(homs, h, w)
    assert plan is not None and plan[0] == 2

  def test_plan_window_escalation(self, rng):
    """Horizontal scale ~1.5 needs the 3-window variant."""
    p, h, w = 2, 32, 768
    planes = _mpi(rng, p, h, w)
    hom = np.array([[1.5, 0.005, 20.0], [0.005, 1, 2.0], [0, 0, 1]],
                   np.float32)
    homs = jnp.asarray(np.broadcast_to(hom, (p, 3, 3)))
    plan = rp._plan_shared(homs, h, w)
    assert plan is not None and plan[1] == 3
    got = rp._SHARED[plan](planes[None], homs[None])[0]
    want = rp.reference_render(planes, homs)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=1e-4, rtol=0)

  def test_gradients_through_shared_vjp(self, rng):
    p, h, w = 2, 32, 256
    planes = _mpi(rng, p, h, w)
    depths = inv_depths(1.0, 100.0, p)
    homs = rp.pixel_homographies(
        _pose(**ROTATION), depths, _intrinsics(h, w), h, w)[:, 0]
    g_shared = jax.grad(
        lambda x: rp.render_mpi_fused(x, homs, separable=False).sum())(planes)
    g_ref = jax.grad(lambda x: rp.reference_render(x, homs).sum())(planes)
    np.testing.assert_allclose(
        np.asarray(g_shared), np.asarray(g_ref), atol=1e-4, rtol=0)

  def test_separable_flag_on_nonseparable_pose_raises(self, rng):
    """separable=True with a rotating pose must raise, not render the
    wrong pixels through the row-independent kernel."""
    p, h, w = 2, 24, 256
    planes = _mpi(rng, p, h, w)
    depths = inv_depths(1.0, 100.0, p)
    homs = rp.pixel_homographies(
        _pose(**ROTATION), depths, _intrinsics(h, w), h, w)[:, 0]
    with pytest.raises(ValueError, match="not separable"):
      rp.render_mpi_fused(planes, homs, separable=True)

  def test_traced_checked_call_raises(self, rng):
    """Under jit no envelope check can run: check=True must raise, never
    silently render unchecked taps (the round-2 silent-wrong-pixels bug)."""
    p, h, w = 2, 24, 256
    planes = _mpi(rng, p, h, w)
    depths = inv_depths(1.0, 100.0, p)

    def render(pose):
      homs = rp.pixel_homographies(
          pose, depths, _intrinsics(h, w), h, w)[:, 0]
      return rp.render_mpi_fused(planes, homs)

    with pytest.raises(ValueError, match="concrete homographies"):
      jax.jit(render)(_pose(**ROTATION))

  def test_traced_unchecked_optin_matches_oracle(self, rng):
    """check=False under jit runs the conservative (3, 3) shared kernel;
    for an in-envelope pose it must match the oracle exactly."""
    p, h, w = 2, 24, 256
    planes = _mpi(rng, p, h, w)
    depths = inv_depths(1.0, 100.0, p)

    def render(pose):
      homs = rp.pixel_homographies(
          pose, depths, _intrinsics(h, w), h, w)[:, 0]
      return rp.render_mpi_fused(planes, homs, check=False)

    got = jax.jit(render)(_pose(**ROTATION))
    homs = rp.pixel_homographies(
        _pose(**ROTATION), depths, _intrinsics(h, w), h, w)[:, 0]
    want = rp.reference_render(planes, homs)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-4, rtol=0)

  @pytest.mark.xfail(
      strict=False,
      reason="pre-existing (seed b1e451b): a handful of boundary pixels "
             "(~0.2% of elements, up to ~0.5 abs) diverge between the "
             "shared-gather kernel and the oracle for some random poses — "
             "a real tap-coverage edge case at window seams, not a "
             "tolerance artifact; tracked as a kernel bug, not hidden by "
             "loosening atol 2500x")
  def test_property_random_poses_accepted_match_rejected_fallback(self, rng):
    """Property sweep (VERDICT r2 item 5): for random poses, plan-accepted
    => shared kernel output matches the oracle within the parity budget;
    plan-rejected => the public entry point still matches (XLA fallback).
    Either way no pose may render dropped-tap partial sums."""
    p, h, w = 2, 32, 256
    depths = inv_depths(1.0, 100.0, p)
    accepted = rejected = 0
    # 36 random modest poses + 4 extreme ones (large tilt/yaw) that must
    # overflow the band/window coverage and exercise the rejection side.
    extremes = [dict(rx=0.35), dict(ry=-0.5), dict(rx=-0.3, ry=0.3),
                dict(rx=0.2, tz=0.9)]
    for i in range(40):
      r = np.random.default_rng(1000 + i)
      planes = _mpi(r, p, h, w)
      if i < len(extremes):
        kw = extremes[i]
      else:
        kw = dict(
            tx=float(r.uniform(-0.3, 0.3)), ty=float(r.uniform(-0.2, 0.2)),
            tz=float(r.uniform(-0.3, 0.3)), rx=float(r.uniform(-0.08, 0.08)),
            ry=float(r.uniform(-0.08, 0.08)))
      homs = rp.pixel_homographies(
          _pose(**kw), depths, _intrinsics(h, w), h, w)[:, 0]
      plan = rp._plan_shared(homs, h, w)
      want = np.asarray(rp.reference_render(planes, homs))
      if plan is not None:
        accepted += 1
        got = np.asarray(rp._SHARED[plan](planes[None], homs[None])[0])
      else:
        rejected += 1
        got = np.asarray(rp.render_mpi_fused(planes, homs, separable=False))
      np.testing.assert_allclose(got, want, atol=1e-3, rtol=0,
                                 err_msg=f"pose {kw}, plan {plan}")
    # The sweep must exercise both sides of the envelope.
    assert accepted >= 5, f"only {accepted}/40 poses accepted"
    assert rejected >= 1, f"no pose rejected; widen the sweep"


class TestRenderMpiIntegration:

  def test_fused_pallas_method_matches_scan(self, rng):
    p, h, w, b = 4, 24, 256, 2
    mpi = jnp.asarray(rng.uniform(0, 1, (b, h, w, p, 4)).astype(np.float32))
    depths = inv_depths(1.0, 100.0, p)
    pose = jnp.concatenate([_pose(**TRANSLATION), _pose(**ROTATION)])
    k = jnp.concatenate([_intrinsics(h, w)] * b)
    got = render.render_mpi(mpi, pose, depths, k,
                            convention=Convention.EXACT, method="fused_pallas")
    want = render.render_mpi(mpi, pose, depths, k,
                             convention=Convention.EXACT, method="scan")
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=1e-4, rtol=0)


class TestBatchedKernel:
  """One kernel launch for a whole batch (batch grid axis, VERDICT r2
  item 6): batched output must equal per-entry renders bit-for-bit."""

  def test_batched_equals_per_entry(self, rng):
    b, p, h, w = 3, 3, 32, 256
    depths = inv_depths(1.0, 100.0, p)
    planes_b = jnp.stack([_mpi(rng, p, h, w) for _ in range(b)])
    kws = [dict(tx=0.05), dict(ry=0.01, tx=0.02), dict(rx=-0.008, tz=0.03)]
    homs_b = jnp.stack([
        rp.pixel_homographies(_pose(**kw), depths, _intrinsics(h, w),
                              h, w)[:, 0] for kw in kws])
    got = rp.render_mpi_fused(planes_b, homs_b, separable=False)
    assert got.shape == (b, 3, h, w)
    for i in range(b):
      single = rp.render_mpi_fused(planes_b[i], homs_b[i], separable=False)
      np.testing.assert_array_equal(np.asarray(got[i]), np.asarray(single))

  def test_batched_separable_equals_per_entry(self, rng):
    b, p, h, w = 2, 3, 24, 256
    depths = inv_depths(1.0, 100.0, p)
    planes_b = jnp.stack([_mpi(rng, p, h, w) for _ in range(b)])
    homs_b = jnp.stack([
        rp.pixel_homographies(_pose(tx=0.04 * (i + 1)), depths,
                              _intrinsics(h, w), h, w)[:, 0]
        for i in range(b)])
    got = rp.render_mpi_fused(planes_b, homs_b, separable=True)
    for i in range(b):
      single = rp.render_mpi_fused(planes_b[i], homs_b[i], separable=True)
      np.testing.assert_array_equal(np.asarray(got[i]), np.asarray(single))

  def test_batched_gradients_match(self, rng):
    b, p, h, w = 2, 2, 24, 128
    depths = inv_depths(1.0, 100.0, p)
    planes_b = jnp.stack([_mpi(rng, p, h, w) for _ in range(b)])
    homs_b = jnp.stack([
        rp.pixel_homographies(_pose(tx=0.03), depths, _intrinsics(h, w),
                              h, w)[:, 0] for _ in range(b)])
    g = jax.grad(lambda x: rp.render_mpi_fused(
        x, homs_b, separable=False).sum())(planes_b)
    g_ref = jax.grad(lambda x: rp._reference_render_batch(
        x, homs_b).sum())(planes_b)
    np.testing.assert_allclose(
        np.asarray(g), np.asarray(g_ref), atol=1e-4, rtol=0)


def _rot_pose_deg(deg, axis="roll", tx=0.02):
  a = np.radians(deg)
  c, s = np.cos(a), np.sin(a)
  pose = np.eye(4, dtype=np.float32)
  if axis == "roll":
    pose[:3, :3] = [[c, -s, 0], [s, c, 0], [0, 0, 1]]
  elif axis == "yaw":
    pose[:3, :3] = [[c, 0, s], [0, 1, 0], [-s, 0, c]]
  else:  # pitch
    pose[:3, :3] = [[1, 0, 0], [0, c, -s], [0, s, c]]
  pose[0, 3] = tx
  return jnp.asarray(pose)[None]


class TestBandedTier:
  """Per-row banded middle tier (VERDICT r3 item 3): large rotations render
  through a Pallas kernel instead of falling 45x to the XLA gather path;
  dispatch chains shared -> banded -> XLA."""

  def _homs(self, deg, h, w, p=3, axis="roll"):
    depths = inv_depths(1.0, 100.0, p)
    return rp.pixel_homographies(
        _rot_pose_deg(deg, axis), depths, _intrinsics(h, w), h, w)[:, 0]

  def test_fallback_chain_tiering(self):
    """Small pose -> shared plan; mid pose -> banded only; extreme -> None.

    H = 144 so the tallest (128-row) band member cannot trivially hold
    the whole image — at H <= bandg the banded tier covers ANY one-signed
    pose (the band IS the image) and no rotation is 'extreme'."""
    h, w = 144, 384
    small = self._homs(0.2, h, w)
    mid = self._homs(10.0, h, w)
    extreme = self._homs(40.0, h, w)
    assert rp._plan_shared(np.asarray(small), h, w) is not None
    assert rp._plan_shared(np.asarray(mid), h, w) is None
    assert rp._plan_banded(np.asarray(mid), h, w) is not None
    assert rp._plan_banded(np.asarray(extreme), h, w) is None

  @pytest.mark.parametrize("deg,axis", [
      (6.0, "roll"), (10.0, "roll"), (10.0, "yaw"), (12.0, "pitch"),
  ])
  def test_banded_parity_vs_oracle(self, rng, deg, axis):
    p, h, w = 3, 48, 384
    planes = _mpi(rng, p, h, w)
    homs = self._homs(deg, h, w, p, axis)
    bplan = rp._plan_banded(np.asarray(homs), h, w)
    assert bplan is not None, (deg, axis)
    got = rp._make_banded(bplan)(planes[None], homs[None])[0]
    want = rp.reference_render(planes, homs)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-4, rtol=0)

  def test_checked_dispatch_uses_banded(self, rng):
    """render_mpi_fused(check=True) on a mid pose renders banded pixels
    (== oracle), not the shared kernel's or a silent fallback."""
    p, h, w = 3, 48, 384
    planes = _mpi(rng, p, h, w)
    homs = self._homs(10.0, h, w, p)
    got = rp.render_mpi_fused(planes, homs)
    want = rp.reference_render(planes, homs)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-4, rtol=0)

  def test_plan_fused_returns_banded_bundle(self):
    h, w = 48, 384
    homs = self._homs(10.0, h, w)
    bundle = rp.plan_fused(homs, h, w)
    assert bundle is not None
    assert bundle["separable"] is False
    assert bundle["plan"][0] == "banded"
    assert bundle["adj_plan"] is None  # XLA backward for the middle tier

  def test_explicit_banded_plan_under_jit(self, rng):
    """A plan_fused banded bundle drives the kernel under jit (the planned
    train-step path: poses are batch data, plans are host-side)."""
    p, h, w = 3, 48, 384
    planes = _mpi(rng, p, h, w)
    homs = self._homs(10.0, h, w, p)
    bundle = rp.plan_fused(homs, h, w)

    @jax.jit
    def f(pl_, hh):
      return rp.render_mpi_fused(pl_, hh, separable=False, check=False,
                                 plan=bundle["plan"],
                                 adj_plan=bundle["adj_plan"])

    got = f(planes, homs)
    want = rp.reference_render(planes, homs)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-4, rtol=0)

  def test_banded_gradient_matches_xla(self, rng):
    p, h, w = 2, 32, 384
    planes = _mpi(rng, p, h, w)
    homs = self._homs(8.0, h, w, p)
    assert rp._plan_shared(np.asarray(homs), h, w) is None
    assert rp._plan_banded(np.asarray(homs), h, w) is not None
    g = jax.grad(lambda x: rp.render_mpi_fused(x, homs).sum())(planes)
    g_ref = jax.grad(
        lambda x: rp.reference_render(x, homs).sum())(planes)
    np.testing.assert_allclose(
        np.asarray(g), np.asarray(g_ref), atol=1e-4, rtol=0)

  def test_banded_batched_equals_per_entry(self, rng):
    b, p, h, w = 2, 2, 32, 384
    planes_b = jnp.stack([_mpi(rng, p, h, w) for _ in range(b)])
    homs_b = jnp.stack([
        self._homs(6.0 + 2 * i, h, w, p) for i in range(b)])
    bplan = rp._plan_banded(np.asarray(homs_b), h, w)
    assert bplan is not None
    got = rp._make_banded(bplan)(planes_b, homs_b)
    for i in range(b):
      single = rp._make_banded(bplan)(planes_b[i][None], homs_b[i][None])[0]
      np.testing.assert_array_equal(np.asarray(got[i]), np.asarray(single))

  @pytest.mark.xfail(
      strict=False,
      reason="pre-existing (seed b1e451b): ~15-deg yaw poses leave ~0.2% "
             "of pixels (up to ~0.5 abs at atol=2e-4) off the oracle in "
             "the banded tier — same band-edge tap-coverage defect as the "
             "shared-kernel property sweep; pinned, not tolerated away")
  def test_banded_property_sweep(self, rng):
    """Random mid-size rotations: plan-accepted => banded matches oracle;
    rejected => checked dispatch still matches (XLA fallback)."""
    p, h, w = 2, 32, 256
    depths = inv_depths(1.0, 100.0, p)
    planes = _mpi(rng, p, h, w)
    accepted = 0
    for i in range(12):
      deg = float(rng.uniform(2.0, 20.0))
      axis = ("roll", "yaw", "pitch")[i % 3]
      homs = rp.pixel_homographies(
          _rot_pose_deg(deg, axis, tx=float(rng.uniform(-0.05, 0.05))),
          depths, _intrinsics(h, w), h, w)[:, 0]
      want = rp.reference_render(planes, homs)
      got = rp.render_mpi_fused(planes, homs)
      np.testing.assert_allclose(
          np.asarray(got), np.asarray(want), atol=2e-4, rtol=0,
          err_msg=f"deg={deg} axis={axis}")
      if rp._plan_banded(np.asarray(homs), h, w) is not None:
        accepted += 1
    assert accepted >= 4, f"banded tier accepted only {accepted}/12 poses"


def _roll_homs(h, w, p, deg, tx=0.0):
  """In-plane roll: v drifts with the tile column, escalating the
  SHARED_LEVELS slice ladder at small geometries (3 deg -> (32, 48),
  6 deg -> (40, 64) at 64x384; 9+ deg falls to the banded tier)."""
  rz = np.radians(deg)
  pose = np.eye(4, dtype=np.float32)
  c, s = np.cos(rz), np.sin(rz)
  pose[:3, :3] = np.array([[c, -s, 0], [s, c, 0], [0, 0, 1]], np.float32)
  pose[0, 3] = tx
  depths = inv_depths(1.0, 100.0, p)
  return rp.pixel_homographies(
      jnp.asarray(pose)[None], depths, _intrinsics(h, w), h, w)[:, 0]


class TestSharedLadderLevels:
  """Parity coverage for the wide-slice SHARED_LEVELS ladder (round-4
  forward variants that previously only the TPU bench would exercise)."""

  @pytest.mark.parametrize("deg,level", [(3.0, (32, 48)), (6.0, (40, 64))])
  def test_wide_level_parity_vs_reference(self, rng, deg, level):
    p, h, w = 3, 64, 384
    planes = _mpi(rng, p, h, w)
    homs = _roll_homs(h, w, p, deg)
    plan = rp._plan_shared(homs, h, w)
    assert plan is not None and (plan[2], plan[3]) == level, (
        f"roll {deg} deg planned {plan}; expected level {level}")
    got = rp._SHARED[plan](planes[None], homs[None])[0]
    want = rp.reference_render(planes, homs)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=1e-3, rtol=0)

  def test_checked_dispatch_walks_the_ladder(self, rng):
    """render_mpi_fused(check=True) on a wide-ladder pose matches the
    reference (the checked path plans and runs the wide level)."""
    p, h, w = 3, 64, 384
    planes = _mpi(rng, p, h, w)
    homs = _roll_homs(h, w, p, 6.0)
    got = rp.render_mpi_fused(planes, homs, separable=False)
    want = rp.reference_render(planes, homs)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=1e-3, rtol=0)

  def test_unplanned_unchecked_conservative_covers_ladder(self, rng):
    """check=False with NO plan runs the top-ladder conservative kernel:
    a pose that plans a wide level must still render correctly (the
    PLAN_UNSET default used to run the base level and would drop taps)."""
    p, h, w = 3, 64, 384
    planes = _mpi(rng, p, h, w)
    homs = _roll_homs(h, w, p, 6.0)
    assert rp.fits_envelope(homs, h, w, False)
    got = jax.jit(
        lambda pl_, hh: rp.render_mpi_fused(pl_, hh, separable=False,
                                            check=False))(planes, homs)
    want = rp.reference_render(planes, homs)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=1e-3, rtol=0)

  def test_wide_level_gradients_match_xla(self, rng):
    """End-to-end grad through the checked dispatch at a wide ladder
    level (the restored Pallas backward for above-base poses)."""
    p, h, w = 2, 64, 384
    planes = _mpi(rng, p, h, w)
    homs = _roll_homs(h, w, p, 3.0)
    plan = rp._plan_shared(homs, h, w)
    assert plan is not None and (plan[2], plan[3]) != (rp.G_SHARED,
                                                       rp.G_BAND)
    g_got = jax.grad(
        lambda x: rp.render_mpi_fused(x, homs, separable=False).sum())(
            planes)
    g_ref = jax.grad(lambda x: rp.reference_render(x, homs).sum())(planes)
    np.testing.assert_allclose(
        np.asarray(g_got), np.asarray(g_ref), atol=1e-3, rtol=0)


class TestBandedTallMembers:
  """The (96, 48) / (128, 64) banded family members: rotation envelope
  past the old (64, 32) cap (at 1080p: yaw to ~24 deg, roll to ~24 deg;
  measured by the host planners — see the roofline addendum)."""

  def _homs(self, deg, h, w, p=3, axis="roll"):
    depths = inv_depths(1.0, 100.0, p)
    return rp.pixel_homographies(
        _rot_pose_deg(deg, axis), depths, _intrinsics(h, w), h, w)[:, 0]

  @pytest.mark.parametrize("deg,min_slice", [(13.0, 48), (20.0, 64)])
  def test_tall_member_parity_vs_oracle(self, rng, deg, min_slice):
    p, h, w = 3, 64, 384
    planes = _mpi(rng, p, h, w)
    homs = self._homs(deg, h, w, p)
    assert rp._plan_shared(np.asarray(homs), h, w) is None
    bplan = rp._plan_banded(np.asarray(homs), h, w)
    assert bplan is not None, deg
    assert bplan[2] >= min_slice, (
        f"roll {deg} deg picked {bplan}; expected a tall member "
        f"(slice >= {min_slice}) — the cheap members must not cover it")
    got = rp._make_banded(bplan)(planes[None], homs[None])[0]
    want = rp.reference_render(planes, homs)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-4, rtol=0)

  def test_old_family_cap_now_covered(self, rng):
    """A pose the pre-widening family rejected (roll 20 deg) renders
    through the checked dispatch and matches the oracle."""
    p, h, w = 3, 64, 384
    planes = _mpi(rng, p, h, w)
    homs = self._homs(20.0, h, w, p)
    got = rp.render_mpi_fused(planes, homs)
    want = rp.reference_render(planes, homs)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-4, rtol=0)

  def test_tall_member_gradients_via_xla_vjp(self, rng):
    """The tall members keep the banded tier's XLA backward (adj_plan
    None by design; artifacts/tier_traffic*.json records zero training
    traffic here)."""
    p, h, w = 2, 64, 384
    planes = _mpi(rng, p, h, w)
    homs = self._homs(13.0, h, w, p)
    g_got = jax.grad(
        lambda x: rp.render_mpi_fused(x, homs).sum())(planes)
    g_ref = jax.grad(lambda x: rp.reference_render(x, homs).sum())(planes)
    np.testing.assert_allclose(
        np.asarray(g_got), np.asarray(g_ref), atol=1e-4, rtol=0)
