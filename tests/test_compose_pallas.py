"""Pallas over-composite kernel vs the lax.scan reference implementation.

Runs in Pallas interpret mode on the CPU test mesh (conftest.py); the kernel
itself is exercised unmodified on TPU by bench.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_vision_tpu.core import compose
from mpi_vision_tpu.kernels import compose_pallas


def _random_mpi(rng, p, b, h, w, dtype=np.float32):
  rgba = rng.uniform(0.0, 1.0, size=(p, b, h, w, 4)).astype(dtype)
  return jnp.asarray(rgba)


@pytest.mark.parametrize(
    "p,b,h,w",
    [
        (1, 1, 8, 128),     # single plane: alpha ignored, out == rgb
        (10, 2, 16, 128),   # fixture-like
        (4, 1, 30, 100),    # non-tile-aligned H and W
        (32, 1, 40, 256),   # bench-like plane count
    ],
)
def test_matches_scan(rng, p, b, h, w):
  rgba = _random_mpi(rng, p, b, h, w)
  got = compose_pallas.over_composite_pallas(rgba)
  want = compose.over_composite_scan(rgba)
  np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)


def test_unbatched_layout(rng):
  rgba = _random_mpi(rng, 6, 1, 24, 136)[:, 0]  # [P, H, W, 4]
  got = compose_pallas.over_composite_pallas(rgba)
  want = compose.over_composite_scan(rgba)
  assert got.shape == (24, 136, 3)
  np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)


def test_multi_tile_grid(rng):
  # H and W both exceed one tile so the accumulator is reused across tiles.
  rgba = _random_mpi(rng, 3, 1, 300, 560)
  got = compose_pallas.over_composite_pallas(rgba)
  want = compose.over_composite_scan(rgba)
  np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)


def test_bfloat16_accumulates_in_f32(rng):
  rgba = _random_mpi(rng, 16, 1, 16, 128)
  got = compose_pallas.over_composite_pallas(rgba.astype(jnp.bfloat16))
  want = compose.over_composite_scan(rgba)
  assert got.dtype == jnp.bfloat16
  # Tight enough to fail under a bf16 accumulator (max err ~8.7e-3 on this
  # config) while f32 accumulation of bf16 inputs stays well under.
  np.testing.assert_allclose(
      np.asarray(got, np.float32), np.asarray(want), atol=5e-3)


def test_via_dispatcher(rng):
  rgba = _random_mpi(rng, 5, 2, 16, 128)
  got = compose.over_composite(rgba, method="pallas")
  want = compose.over_composite(rgba, method="scan")
  np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)


def test_gradients_match_scan(rng):
  rgba = _random_mpi(rng, 4, 1, 8, 128)

  def loss_pallas(x):
    return jnp.sum(compose_pallas.over_composite_pallas(x) ** 2)

  def loss_scan(x):
    return jnp.sum(compose.over_composite_scan(x) ** 2)

  g_pallas = jax.grad(loss_pallas)(rgba)
  g_scan = jax.grad(loss_scan)(rgba)
  np.testing.assert_allclose(
      np.asarray(g_pallas), np.asarray(g_scan), atol=1e-5)
