"""Viewer export tests + BASELINE config-1 parity on the baked fixture MPI.

The ``tests/fixtures/scene_009`` PNGs are the reference repo's only test
data (a real 10-plane 640x400 MPI; SURVEY.md §4): compositing them to the
frontal view against the torch oracle is benchmark config #1.
"""

import os

import jax.numpy as jnp
import numpy as np
import pytest
import torch

from mpi_vision_tpu import viewer
from mpi_vision_tpu.core import compose
from mpi_vision_tpu.torchref import oracle

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "scene_009")


@pytest.fixture(scope="module")
def fixture_mpi():
  return viewer.load_fixture_mpi(FIXTURES)


class TestFixtureComposite:

  def test_config1_frontal_composite_matches_torch(self, fixture_mpi):
    """BASELINE config 1: over-composite the baked MPI to the frontal view."""
    planes = jnp.moveaxis(jnp.asarray(fixture_mpi), 2, 0)  # [P, H, W, 4]
    got = compose.over_composite(planes)
    want = oracle.over_composite(torch.from_numpy(
        np.moveaxis(fixture_mpi, 2, 0))).numpy()
    assert got.shape == (400, 640, 3)
    l1 = np.abs(np.asarray(got) - want).mean()
    assert l1 <= 1e-3, f"per-pixel L1 {l1} above parity budget"

  def test_fixture_shape(self, fixture_mpi):
    assert fixture_mpi.shape == (400, 640, 10, 4)
    assert fixture_mpi[..., :3].min() >= -1.0
    assert 0.0 <= fixture_mpi[..., 3].min() <= fixture_mpi[..., 3].max() <= 1.0


class TestPngRoundtrip:

  def test_layer_png_roundtrip(self, rng, tmp_path):
    mpi = rng.uniform(-1, 1, (16, 24, 3, 4)).astype(np.float32)
    mpi[..., 3] = (mpi[..., 3] + 1) / 2  # alpha in (0,1)
    paths = viewer.save_layer_pngs(mpi, str(tmp_path))
    assert [os.path.basename(p) for p in paths] == [
        "mpi00.png", "mpi01.png", "mpi02.png"]
    back = viewer.load_fixture_mpi(str(tmp_path), prefix="mpi")
    # 8-bit quantization budget: half a step in [-1,1] rgb / [0,1] alpha.
    np.testing.assert_allclose(back[..., :3], mpi[..., :3], atol=1.1 / 255)
    np.testing.assert_allclose(back[..., 3], mpi[..., 3], atol=0.6 / 255)


class TestHtmlExport:

  def test_export_html_structure(self, fixture_mpi, tmp_path):
    out = viewer.export_viewer_html(
        fixture_mpi[:, :, :3], str(tmp_path / "v.html"))
    html = open(out).read()
    assert html.count("data:image/png;base64,") == 3
    assert "__MPI_SOURCES__" not in html and "__NEAR__" not in html
    assert '"w": 640' not in html  # substituted, not templated json
    assert "perspective" in html and "translateZ" in html

  def test_data_uri(self):
    uri = viewer.to_data_uri(b"\x89PNG")
    assert uri.startswith("data:image/png;base64,")


class TestViewerFeatures:
  """The reference template's inspection/motion surface (VERDICT r2 item 7):
  depth heatmaps, sway/wander, URL params + external sequences, minis and
  under/over selection — asserted structurally on the exported HTML."""

  @pytest.fixture(scope="class")
  def html(self, fixture_mpi, tmp_path_factory):
    out = viewer.export_viewer_html(
        np.asarray(fixture_mpi[:, :, :3]),
        str(tmp_path_factory.mktemp("v") / "v.html"))
    return open(out).read()

  def test_silhouette_modes(self, html):
    """Excluded-layer black/white silhouettes (the reference's
    feColorMatrix white/black inspection filters, template:693-698)."""
    assert "silh-black" in html and "silh-white" in html
    assert "brightness(0) invert(1)" in html    # white silhouette filter
    assert 'e.key === "x"' in html              # the mode-cycle key
    assert "setSilhMode" in html

  def test_depth_colormap_modes(self, html):
    # Two procedural colormaps tinting layers through their alpha masks.
    assert "function turbo(" in html
    assert "function magma(" in html
    assert "MAGMA_ANCHORS" in html
    assert "maskImage" in html and "depthmap" in html
    assert 'e.key === "d"' in html

  def test_sway_and_wander_motion(self, html):
    assert '"sway"' in html and '"wander"' in html
    assert "requestAnimationFrame(tick)" in html
    assert 'e.key === "s"' in html and 'e.key === "w"' in html

  def test_url_params_and_external_sequences(self, html):
    assert "URLSearchParams" in html
    # $$ -> zero-padded index for external mpi$$.png sequences.
    assert 'replace("$$"' in html and 'q.get("url")' in html
    for param in ("near", "far", "fov", "depth", "mini", "solo"):
      assert f'"{param}"' in html, param
    assert 'q.get("move")' in html

  def test_minis_and_under_over(self, html):
    assert 'id="minis"' in html
    assert '"under"' in html and '"over"' in html
    assert 'e.key === "["' in html and 'e.key === "]"' in html
    assert 'e.key === "m"' in html

  def test_colormap_endpoints_sane(self, html):
    """The magma anchor table must start near black and end near white —
    guards against an accidentally reversed/garbled table."""
    import re

    anchors = re.search(r"MAGMA_ANCHORS = \[([^;]+)\];", html).group(1)
    rows = re.findall(r"\[(\d+), (\d+), (\d+)\]", anchors)
    first = tuple(int(v) for v in rows[0])
    last = tuple(int(v) for v in rows[-1])
    assert sum(first) < 40 and sum(last) > 550
