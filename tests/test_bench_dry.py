"""The headline bench's decision path, off-chip (BENCH_DRY=1).

Round 4's only bench attempt died before timing anything: a tier guard
went stale when the slice ladder widened the shared envelope past the
10-degree pose the guard assumed banded (ADVICE r4, high). Every part of
that failure was host math — plan_fused, the tier guards, the banded-pose
sweep — and none of it needs a TPU. This test runs bench.py in its
dry-run mode in a subprocess (own env: the bench must plan at 1080p with
the REAL planners, not the conftest mesh) so guard rot can never again
survive to a tunnel window.
"""

import json
import os
import subprocess
import sys


def test_bench_dry_run_plans_all_tiers():
  repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
  env = dict(os.environ)
  env.pop("PALLAS_AXON_POOL_IPS", None)
  env["JAX_PLATFORMS"] = "cpu"
  env["BENCH_DRY"] = "1"
  proc = subprocess.run(
      [sys.executable, os.path.join(repo, "bench.py")],
      capture_output=True, text=True, timeout=1200, env=env, cwd=repo)
  assert proc.returncode == 0, (
      f"bench dry run failed:\n{proc.stderr[-2000:]}")
  out = json.loads(proc.stdout.strip().splitlines()[-1])
  assert out["metric"] == "bench_dry_run" and out["value"] == 1
  # The swept banded pose must sit beyond the shared ladder's ~13-degree
  # 1080p envelope; if this moves, re-check the sweep range in bench.py.
  assert 13.0 < out["banded_deg"] <= 24.0
  assert "dry banded: plan ok" in proc.stderr
