"""bench/serve_load.py dry mode on CPU (subprocess) — tier-1 smoke.

Mirrors tests/test_bench_dry.py: the load generator's decision path
(service wiring, warm-up, closed-loop workers, JSON contract) is all
host+CPU-sized work, so guard rot in it is caught here rather than in a
TPU window. Asserts the single JSON line carries the serving headline
fields: renders_per_sec, p50_ms, p99_ms, cache_hit_rate.

The ``--chaos`` variant is the resilience layer's end-to-end smoke: a
seeded fault schedule injects transient errors and slow dispatches into
real closed-loop traffic, and the run must still complete with the
chaos accounting (injected counts, retries, breaker state) in the JSON.
"""

import json
import os
import subprocess
import sys

import pytest


_SHARED_DRY_MODES = [
    ("trace", ["--trace"]),
    ("ab", ["--ab"]),
    ("edge_ab", ["--edge-ab", "--zipf-poses", "16"]),
    # --duration 1: the tiled contract (parity + cull accounting) needs
    # poses served, not a long window.
    ("tiled_ab", ["--tiled-ab", "--duration", "1"]),
    ("asset_ab", ["--asset-ab"]),
    ("session_ab", ["--session-ab"]),
    # {incident_dir} is substituted by the fixture (tmp dir per run).
    ("overload_ab", ["--overload-ab", "--incident-dir", "{incident_dir}"]),
    ("chaos", ["--chaos"]),
]

_SHARED_DRY_DRIVER = """
import json, os, sys
repo = sys.argv[1]
sys.path.insert(0, os.path.join(repo, "bench"))
import serve_load
for name, argv in json.loads(sys.argv[2]):
  print("shared-dry: running %s %r" % (name, argv), file=sys.stderr)
  rc = serve_load.main(argv)
  if rc != 0:
    print("shared-dry: %s exited %d" % (name, rc), file=sys.stderr)
    sys.exit(rc)
"""


def _drive_shared(modes, timeout_s=1200):
  """Run a list of ``(name, argv)`` serve_load modes through ONE child
  interpreter; returns {mode_name: parsed JSON record}."""
  repo = os.path.dirname(os.path.dirname(os.path.dirname(
      os.path.abspath(__file__))))
  sys.path.insert(0, repo)
  from _cpu_mesh import hardened_env

  env = hardened_env(1)
  env["SERVE_LOAD_DRY"] = "1"
  env["JAX_COMPILATION_CACHE_DIR"] = os.path.join(repo, ".jax_cache")
  proc = subprocess.run(
      [sys.executable, "-c", _SHARED_DRY_DRIVER, repo, json.dumps(modes)],
      capture_output=True, text=True, timeout=timeout_s, env=env, cwd=repo)
  assert proc.returncode == 0, (
      f"shared dry driver failed:\n{proc.stderr[-3000:]}")
  lines = [l for l in proc.stdout.strip().splitlines()
           if l.startswith("{")]
  assert len(lines) == len(modes), (
      f"expected {len(modes)} JSON lines, got {len(lines)}:"
      f"\n{proc.stdout[-2000:]}")
  return {name: json.loads(line)
          for (name, _), line in zip(modes, lines)}


@pytest.fixture(scope="module")
def shared_dry_runs(tmp_path_factory):
  """ONE subprocess runs every single-process dry smoke back to back.

  Each dry run is a full JAX child-process spawn — the unit of cost in
  this file — but the single-process modes (trace, ab, edge-ab,
  tiled-ab, asset-ab, session-ab, overload-ab, chaos) share no
  cross-run state: every ``serve_load.main(argv)`` call builds its own
  scenes, service, and workers and tears them down. Driving them
  sequentially through one interpreter pays the import + jit-warmup tax
  once (later runs also reuse the process-global compile cache). Budget
  reclamation round 3 merged the headline+trace spawns; round 4 folded
  the other single-process smokes in; round 5 (session tier) adds
  session-ab and folds the overload-ab spawn in too — reclaiming more
  spawn tax than the new session arms add. The cluster drills keep
  their own pool-spawning subprocess (shared among themselves, below).
  Returns {mode_name: parsed JSON record}.
  """
  incident_dir = str(tmp_path_factory.mktemp("bb"))
  modes = [(name, [a.replace("{incident_dir}", incident_dir)
                   for a in argv])
           for name, argv in _SHARED_DRY_MODES]
  runs = _drive_shared(modes)
  runs["overload_ab"]["_incident_dir"] = incident_dir
  return runs


@pytest.fixture(scope="module")
def traced_dry_run(shared_dry_runs):
  return shared_dry_runs["trace"]


def test_serve_load_dry_emits_headline_json(traced_dry_run):
  out = traced_dry_run
  assert out["metric"] == "serve_load" and out["dry"] is True
  assert out["device"] == "cpu"
  assert out["renders_per_sec"] > 0
  assert out["p50_ms"] > 0 and out["p99_ms"] >= out["p50_ms"]
  assert 0 <= out["cache_hit_rate"] <= 1
  assert out["requests"] >= out["batches"] >= 1
  assert out["chaos"] is False
  # Pipeline accounting rides every run: the window, the device-idle
  # gap metric, out-of-order/abandoned counters, per-scene breakdown.
  assert out["inflight"] >= 1
  assert set(out["dispatch_gap"]) == {"count", "total_s", "mean_ms",
                                      "max_ms"}
  assert out["abandoned_batches"] == 0
  assert out["out_of_order_completions"] >= 0
  assert out["per_scene"]  # hot-scene breakdown present
  for entry in out["per_scene"].values():
    assert entry["requests"] > 0 and entry["p50_ms"] > 0
  # Outage accounting rides EVERY run (trend across BENCH rounds): the
  # error/resilience counters and breaker state, zeros and all.
  assert set(out["errors"]) == {"transient", "permanent", "deadline"}
  assert out["rejected"] == 0
  assert set(out["resilience"]) >= {"retries", "watchdog_trips",
                                    "fallback_renders", "breaker_opens"}
  assert out["breaker_state"] == "closed"
  # The SLO verdict block rides every run: objectives judged against
  # slow-window attainment. A clean dry run must PASS availability
  # outright (no errors => attainment 1.0).
  slo = out["slo"]
  assert set(slo["objectives"]) == {"availability", "latency",
                                    "latency_p99"}
  avail = slo["objectives"]["availability"]
  assert avail["target"] == 0.99 and avail["attained"] == 1.0
  assert avail["requests"] >= out["requests"]
  assert avail["pass"] is True and avail["burn_slow"] == 0.0
  assert slo["alerts_firing"] == []
  # The quantile-SLO verdict (flight recorder): p99 judged from the
  # pooled native histogram, percentile-true — the block must carry the
  # quantile, the threshold, and the measured window quantile. The
  # per-scene table rides along (bounded; every dry scene scored).
  q99 = slo["objectives"]["latency_p99"]
  assert q99["quantile"] == 0.99 and q99["threshold_ms"] == 1000.0
  assert q99["quantile_ms"] is not None and q99["quantile_ms"] > 0
  assert q99["requests"] >= out["requests"]
  assert q99["pass"] in (True, False)  # judged, not skipped
  per_scene = slo["per_scene"]
  assert per_scene["scenes"] >= 1
  assert isinstance(per_scene["failing"], list)
  # The attribution ledger rides every serve_load run: cells name the
  # dry scenes and the conservation invariant reconciles exactly even
  # under the closed-loop worker pool.
  attrib = out["attrib"]
  assert attrib["cells_total"] >= 1
  assert attrib["conservation"]["ok"] is True
  assert attrib["totals"]["requests"] >= out["requests"]
  assert attrib["top_cells"][0]["scene"].startswith("scene_")


def test_serve_load_trace_dry_smoke(traced_dry_run):
  """The trace-enabled smoke: closed-loop traffic under a live Tracer
  must finish, and the slowest-exemplar span trees must cover the whole
  request path (the acceptance span set + attempt children)."""
  out = traced_dry_run
  assert out["metric"] == "serve_load" and out["dry"] is True
  assert out["renders_per_sec"] > 0
  trace = out["trace"]
  assert trace["finished"] >= out["requests"]
  assert trace["slowest_ms"] and trace["slowest_ms"] > 0
  assert {"queue_wait", "batch_assembly", "dispatch", "attempt", "bake",
          "h2d", "compute", "readback"} <= set(trace["span_names"])


def test_serve_load_ab_dry_smoke(shared_dry_runs):
  """The pipelined-vs-blocking A/B smoke: one process, two measured
  arms, one JSON line. Pins the contract (both arms' headline fields +
  the gap metric that proves/disproves device idle), NOT a dry-mode
  speedup — on 32-px toy scenes per-dispatch host overhead dominates
  and the win only shows at real sizes (recorded per BENCH round)."""
  out = shared_dry_runs["ab"]
  assert out["metric"] == "serve_load_ab" and out["dry"] is True
  assert out["device"] == "cpu"
  assert out["speedup"] and out["speedup"] > 0
  pipelined, blocking = out["pipelined"], out["blocking"]
  assert pipelined["inflight"] >= 2 and blocking["inflight"] == 1
  for arm in (pipelined, blocking):
    assert arm["renders_per_sec"] > 0 and arm["p50_ms"] > 0
    assert set(arm["dispatch_gap"]) == {"count", "total_s", "mean_ms",
                                        "max_ms"}
  # Blocking serializes: every post-completion launch finds the device
  # idle, so its gap metric must have fired.
  assert blocking["dispatch_gap"]["count"] >= 1
  assert blocking["out_of_order_completions"] == 0


def test_serve_load_edge_ab_dry_smoke(shared_dry_runs):
  """The edge-cache A/B smoke: Zipf-distributed poses served through the
  pose-quantized frame cache, then through the raw path, one JSON line.
  Pins the contract (both arms + hit/warp/miss split + p50 fields) and
  that the cache really served the bulk of the Zipf traffic — not a
  dry-mode p50 ordering, which toy scenes could flip on noise."""
  out = shared_dry_runs["edge_ab"]
  assert out["metric"] == "serve_load_edge_ab" and out["dry"] is True
  assert out["device"] == "cpu" and out["zipf_poses"] == 16
  assert out["p50_ms_edge_on"] > 0 and out["p50_ms_edge_off"] > 0
  assert out["value"] and out["value"] > 0
  # The Zipf pool repeats poses, so the cache must have absorbed most
  # lookups (hits + warp serves), with the counts in the report.
  assert out["hits"] + out["warp_serves"] + out["misses"] > 0
  assert out["misses"] >= 1  # cells had to populate
  assert out["hit_rate"] > 0.5
  edge_on = out["edge_on"]
  assert edge_on["edge"]["hit_rate"] == out["hit_rate"]
  assert edge_on["requests"] > 0 and out["edge_off"]["requests"] > 0
  assert "edge" not in out["edge_off"]


def test_serve_load_tiled_ab_dry_smoke(shared_dry_runs):
  """The tile-granular A/B smoke: one depth-stratified scene served
  through the tiled (frustum-culled) path and the monolithic path, one
  JSON line. Pins the contract — both arms' headline fields, the tile
  accounting (the pose pool MUST have culled tiles or the workload is
  broken), and the bit-exact full-coverage parity — NOT a dry-mode
  speedup: on 32-px toy scenes the per-request plan/concat overhead
  dominates and the render-cost win only shows at real sizes (recorded
  per BENCH round)."""
  out = shared_dry_runs["tiled_ab"]
  assert out["metric"] == "serve_load_tiled_ab" and out["dry"] is True
  assert out["device"] == "cpu"
  # The pinned parity: the bench itself aborts (non-zero exit) when the
  # full-coverage pose is not bit-exact, so reaching here with the flag
  # set true is the end-to-end proof; the culled poses must stay at
  # float-rounding scale (conservative frustum + zero-padded sampling).
  assert out["parity"]["full_coverage_bit_exact"] is True
  assert out["parity"]["culled_pose_max_abs_diff"] <= 1e-4
  assert out["p50_ms_tiled"] > 0 and out["p50_ms_full"] > 0
  assert out["value"] and out["value"] > 0
  tiles = out["tiled"]["tiles"]
  assert tiles["tiled_requests"] > 0
  # The panning pose pool must actually exercise the cull: some tiles
  # culled, and the mean touched strictly inside (0, total).
  assert tiles["culled_total"] > 0
  assert 0 < out["tiles_touched_mean"] < out["tiles_total"]
  assert out["tiled"]["tile_cache"]["misses"] >= 1  # per-tile bakes ran
  assert out["full"]["requests"] > 0 and out["tiled"]["requests"] > 0
  assert "tiles" not in out["full"]


def test_serve_load_asset_ab_dry_smoke(shared_dry_runs):
  """The asset delivery tier's tier-1 smoke: manifest + every tile
  asset over real HTTP (cold), full 304 revalidation (warm — the bench
  itself aborts if any conditional GET misses), a full cross-process
  SceneFetcher sync, and the quarter-scene diff re-sync. The PINNED
  acceptance number: diff-sync bytes strictly below both the full-sync
  bytes (the bench aborts otherwise) and the full-checkpoint bytes —
  tiles moved, not frames, not checkpoints."""
  out = shared_dry_runs["asset_ab"]
  assert out["metric"] == "serve_load_asset_ab" and out["dry"] is True
  assert out["cold"]["assets"] == out["tiles_total"] >= 4
  assert out["cold"]["bytes"] > 0
  assert out["warm"]["not_modified"] == out["tiles_total"] + 1  # +manifest
  assert out["warm"]["bytes"] == 0  # 304s carry no bodies
  assert out["full_sync"]["tiles_fetched"] == out["tiles_total"]
  # The diff moved only the mutated quarter — and measurably fewer
  # bytes than shipping the scene as a checkpoint would.
  assert 0 < out["diff_sync"]["tiles_fetched"] < out["tiles_total"]
  assert out["diff_sync"]["bytes"] < out["full_sync"]["bytes"]
  assert out["diff_sync"]["bytes"] < out["full_checkpoint_bytes"]
  assert out["value"] == round(
      out["diff_sync"]["bytes"] / out["full_checkpoint_bytes"], 4)


def test_serve_load_session_ab_dry_smoke(shared_dry_runs):
  """The session tier's tier-1 smoke (PR 20's acceptance pin): the same
  smooth-trajectory pose load driven through streaming sessions and
  through one POST /render per frame, one JSON line. The pins are
  structural, not latency-noise: the session arm's pipelined flushes
  reach a deeper effective concurrency than request-per-frame's (so it
  must not LOSE on throughput), flushes really fuse (>1 poses per
  drain), the trajectory predictor's speculative renders land cells the
  camera then arrives in (prefetch hits > 0), and session frames are
  BIT-IDENTICAL to the unbatched render path (the bench itself aborts
  on a parity mismatch — reaching the JSON is the proof)."""
  out = shared_dry_runs["session_ab"]
  assert out["metric"] == "serve_load_session_ab" and out["dry"] is True
  # Throughput: fusion + pipelining must at least match one-request-at-
  # a-time HTTP (in practice the dry margin is ~2x; >= 1 absorbs noise).
  assert out["value"] >= 1.0
  assert out["frames_per_sec_session"] > 0
  assert out["frames_per_sec_request"] > 0
  # Flight fusion really happened: multi-pose drains, and the fused
  # flushes coalesced into larger device batches than request-per-frame.
  assert out["mean_flush_size"] > 1.0
  assert out["mean_batch_size_session"] > out["mean_batch_size_request"]
  # Trajectory-predictive prefetch: speculative renders were issued and
  # some were consumed as exact edge hits by the advancing camera.
  assert out["prefetch"]["issued"] > 0
  assert out["prefetch"]["hits"] > 0
  assert out["prefetch"]["hit_rate"] > 0
  # The PINNED bit-exactness: streamed frames == unbatched renders.
  assert out["parity"]["bit_exact"] is True and out["parity"]["poses"] >= 1
  session_arm = out["session"]
  # Sessions opened, streamed, and closed cleanly; the /stats session
  # block rode the record.
  sess = session_arm["session"]
  assert sess["enabled"] is True
  assert sess["opened"] >= 1 and sess["closed"] == sess["opened"]
  assert sess["rejected"] == 0 and sess["frame_errors"] == 0
  assert sess["frames"] == session_arm["frames"]
  # Full per-request semantics: every session frame (and prefetch) went
  # through the front door — SLO judged them, and the attribution
  # ledger reconciles exactly with prefetch attributed to its own class.
  assert session_arm["slo"]["pass"] is True
  assert session_arm["attrib"]["conservation"]["ok"] is True
  assert session_arm["device_seconds_by_class"]["prefetch"] > 0
  prefetch_cells = [c for c in session_arm["attrib"]["top_cells"]
                    if c["class"] == "prefetch"]
  assert prefetch_cells and all(
      c["scene"].startswith("scene_") for c in prefetch_cells)
  assert out["request"]["attrib"]["conservation"]["ok"] is True


def test_cluster_kill_failover_drill_on_shared_pool(healed_backends):
  """The multi-host failover drill, in-process on the SESSION pool
  (budget reclamation round 4: this was the ``--cluster`` dry
  subprocess — a whole extra JAX pool spawn for an arc the shared
  3-backend fleet drives in seconds; the bench's cluster JSON contract
  stays covered by the crashloop / chaos-router / autoscale-ab smokes
  below). SIGKILL one backend mid-traffic: requests keep completing,
  attempts fail over to replicas, ONLY the dead backend's breaker
  opens, and the aggregated health view degrades."""
  import json as json_mod
  import urllib.request

  import numpy as np

  from mpi_vision_tpu.serve.cluster import Router

  pool, backends = healed_backends
  router = Router(dict(backends), replication=2, breaker_threshold=2,
                  breaker_reset_s=600.0, render_timeout_s=120.0)
  sids = pool.scene_ids()

  def render(sid):
    body = json_mod.dumps({"scene_id": sid,
                           "pose": np.eye(4).tolist()}).encode()
    return router.forward_render(sid, body)

  try:
    for sid in sids:
      status, _, _ = render(sid)
      assert status == 200
    # Work landed on more than one backend: the ring really shards.
    assert len(router.metrics.snapshot()["forwards"]) >= 2
    # Kill the primary of sids[0] so that scene MUST fail over.
    victim = router.placement(sids[0])[0]
    pool.kill(victim)
    post_kill = 0
    for _ in range(3):
      for sid in sids:
        status, _, _ = render(sid)
        assert status == 200  # replicas absorb every request
        post_kill += 1
    assert post_kill > 0
    snap = router.metrics.snapshot()
    assert snap["failovers"] >= 1
    assert router.breaker_state(victim) == "open"
    for backend in router.backend_ids():
      if backend != victim:
        assert router.breaker_state(backend) == "closed", (
            f"healthy backend {backend} opened")
    assert router.healthz()["status"] == "degraded"
    # Fleet SLO view: the surviving backends still report their slo
    # blocks through the router's aggregation.
    slo = router.stats().get("slo")
    assert slo is not None and slo["backends_reporting"] >= 2
  finally:
    # Re-gate the fleet for whatever module shares the pool next (a
    # failed assertion above still leaves heal_pool to catch it).
    for bid in sorted(pool.addresses()):
      if not pool.alive(bid):
        pool.restart(bid)


# The --chaos-crashloop subprocess smoke retired in budget reclamation
# round 4: its whole arc — kill on every respawn, restart-budget
# containment, quarantine visible at the router, fleet still serving —
# is pinned in-process on the LIVE shared pool by
# test_supervisor.py::test_supervisor_quarantines_a_crash_looper_at_the_budget
# (plus the failover drill above for post-ejection serving), and the
# bench flag wiring stays guarded in test_cli. One fewer 19s JAX spawn.


@pytest.fixture(scope="module")
def cluster_dry_runs():
  """The two cluster drills (router-HA chaos + autoscale A/B) through
  ONE child interpreter — budget reclamation round 5. Each drill still
  spawns its own backend pool (that is the drill), but the parent's
  JAX import + warmup tax is paid once instead of twice. They stay out
  of ``shared_dry_runs``: pool spawns must not contend with the
  single-process modes' in-process servers."""
  return _drive_shared([
      ("chaos_router", ["--cluster", "--chaos-router"]),
      ("autoscale_ab", ["--cluster", "--autoscale-ab"]),
  ])


def test_serve_load_cluster_chaos_router_dry_smoke(cluster_dry_runs):
  """The router-HA drill's tier-1 smoke (ISSUE 15's acceptance pin):
  TWO gossiping router replicas front the pool, closed-loop clients
  hammer the SURVIVOR, and the supervising router is SIGKILLed
  mid-window. The run must record zero failed requests on the survivor,
  a bounded lease takeover, and a backend killed AFTER the takeover
  respawned by the new leader through the --restart-hook webhook."""
  out = cluster_dry_runs["chaos_router"]
  assert out["metric"] == "serve_load" and out["dry"] is True
  assert out["renders_per_sec"] > 0 and out["requests"] > 0
  cluster = out["cluster"]
  assert cluster["backends"] == 3 and cluster["replication"] == 2
  # THE pin: the survivor dropped nothing — before, during, or after
  # the router kill (failure_counts is empty, not merely small).
  assert cluster["failed_requests"] == {}
  assert cluster["post_kill_requests"] > 0
  drill = cluster["chaos_router"]
  assert drill["routers"] == 2
  assert drill["killed_router"] == "routerA"
  assert drill["survivor"] == "routerB"
  # Supervision moved: the survivor reaped the stale lease in bounded
  # time and its own metrics agree it now leads.
  assert drill["lease_taken_over"] is True
  assert drill["takeover_s"] is not None
  assert drill["takeovers_total"] >= 1
  assert drill["lease_held"] == 1
  assert drill["lease_owner"] == "routerB"
  # A backend killed AFTER the takeover was respawned by the NEW
  # leader, via the restart webhook — remote supervision really works.
  assert drill["backend_killed"] is not None
  assert drill["backend_respawned"] is True
  assert drill["respawn_s"] is not None
  assert drill["hook_invocations"] >= 1
  assert drill["hook_failures"] == 0
  # Anti-entropy really ran between the replicas.
  assert drill["gossip"]["rounds"] > 0


def test_serve_load_autoscale_ab_dry_smoke(cluster_dry_runs):
  """The elastic-fleet A/B's tier-1 smoke (PR 19's acceptance pin):
  the same bounded-queue surge replayed against a fixed single-backend
  pool and an autoscaled one, one JSON line. The pins: the autoscaler
  arm GROWS under the surge (warmed admit — the new backend joins the
  ring only after its scene warm-up), HOLDS the availability verdict
  the fixed arm violates (one backend cannot hold the surge inside its
  bounded queue; scaled capacity can — a capacity bound, deterministic
  where dry-scale latency quantiles are not), SHRINKS back in the idle
  tail, and drops ZERO requests inside any scale-down window."""
  out = cluster_dry_runs["autoscale_ab"]
  assert out["metric"] == "serve_load_autoscale_ab" and out["dry"] is True
  fixed, scaled = out["fixed"], out["autoscale"]
  # THE verdict contrast: same ramp, same objective, opposite verdicts.
  assert fixed["slo"]["pass"] is False
  assert scaled["slo"]["pass"] is True
  assert scaled["slo"]["judged_availability"] >= 0.99
  assert fixed["slo"]["judged_availability"] < 0.99
  assert out["value"] is not None and out["value"] > 0
  # The trajectory proof: the pool grew under the surge and shrank in
  # the tail; the fixed arm never moved.
  assert out["grew"] is True and out["shrank"] is True
  assert scaled["backends_max"] == 2 and scaled["backends_final"] == 1
  assert fixed["backends_max"] == 1
  assert scaled["events"]["autoscale_up"] >= 1
  assert scaled["events"]["autoscale_down"] >= 1
  assert scaled["events"]["autoscale_abort"] == 0
  # Drainless scale-down: no client failure inside any retire window.
  assert out["scale_down_window_failed"] == 0
  assert scaled["scale_down_windows"]
  # Both arms carry the sampled fleet timeline (pool size + brownout
  # level over time) — autoscaler off included — plus p99 trajectories.
  for arm in (fixed, scaled):
    assert arm["timeline"] and len(arm["p99_trajectory_ms"]) == 20
    assert {"t", "backends", "ejected",
            "brownout_max_level"} <= set(arm["timeline"][0])
    assert arm["requests"] > 0 and arm["judged_p99_ms"] > 0
  # The autoscaler's own account rides the record: policy counters,
  # decision history, and the per-event timeline.
  snap = scaled["autoscale"]
  assert snap["ups"] >= 1 and snap["downs"] >= 1 and snap["aborts"] == 0
  assert snap["policy"]["ups"] >= 1
  assert any(ev["kind"] == "autoscale_up" for ev in scaled["scale_events"])


def test_serve_load_chaos_dry_smoke(shared_dry_runs):
  """Chaos mode must inject faults AND finish healthy: the workload rides
  retries/fallback instead of aborting, and the JSON carries the
  resilience accounting."""
  out = shared_dry_runs["chaos"]
  assert out["metric"] == "serve_load" and out["dry"] is True
  assert out["chaos"] is True
  assert out["renders_per_sec"] > 0 and out["requests"] > 0
  injected = out["chaos_injected"]
  assert injected["error"] > 0  # the schedule really fired
  # Injected transient faults surface as retries (and possibly breaker
  # opens), not as aborted runs.
  assert out["resilience"]["retries"] > 0
  assert out["breaker_state"] in ("closed", "open", "half_open")
  assert set(out["errors"]) == {"transient", "permanent", "deadline"}
  assert out["chaos_failed_requests"] is not None
  # The verdict block judges the chaos window too (objective, attained,
  # burn rates, pass/fail — whether the fleet RODE OUT the faults).
  # Quantile objectives are scored by their windowed quantile instead of
  # a fractional attainment.
  slo = out["slo"]
  for obj in slo["objectives"].values():
    if "quantile" in obj:
      assert {"quantile", "threshold_ms", "quantile_ms", "burn_fast",
              "burn_slow", "pass"} <= set(obj)
    else:
      assert {"target", "attained", "burn_fast", "burn_slow",
              "pass"} <= set(obj)
  assert slo["objectives"]["availability"]["requests"] >= out["requests"]


def test_serve_load_overload_ab_dry_smoke(shared_dry_runs):
  """The brownout A/B's tier-1 smoke: one process, a ~3x phased
  overload ramp driven twice — ladder armed, then shed-only — and one
  JSON line. Dry scale pins MECHANICS only (same contract as the --ab
  and --tiled-ab dry smokes, where toy-scene verdicts are noise): the
  ladder engages under the ramp and recovers to L0, interactive is
  never shed below L4, neither arm 5xxs, and the JSON carries the full
  acceptance shape. The performance verdict — brownout buys
  interactive goodput and holds the SLO that shed-only violates —
  belongs to real sizes (`--overload-ab --duration 10`, BENCH-style).

  With --incident-dir this smoke also rides the incident-lens arc
  (PR 18): both arms carry an attribution block whose conservation
  invariant holds through real multithreaded load, the per-class
  device-seconds split is computed, and the deterministic incident
  drill captures exactly the induced bundle end-to-end — alert edge ->
  black-box file on disk — without a second subprocess."""
  import pathlib

  out = shared_dry_runs["overload_ab"]
  incident_dir = pathlib.Path(out["_incident_dir"])
  assert out["metric"] == "serve_load_overload_ab" and out["dry"] is True
  assert out["latency_threshold_ms"] > 0  # calibrated, not hardcoded
  brownout, shed_only = out["brownout"], out["shed_only"]
  # Shape: the goodput ratio and verdicts are computed and sane, even
  # though dry scale can't pin which way they fall.
  assert out["interactive_goodput_x"] is not None
  assert out["interactive_goodput_x"] > 0
  assert isinstance(brownout["slo"]["pass"], bool)
  assert isinstance(shed_only["slo"]["pass"], bool)
  # Admission contract: interactive is shed ONLY at L4 — if the ladder
  # never maxed out, interactive sheds must be exactly zero.
  if brownout["max_level"] < 4:
    assert brownout["sheds"]["interactive"] == 0
  assert brownout["requests_ok"]["interactive"] > 0
  # No 5xx storm in either arm: failures stay empty, pressure resolves
  # as sheds (brownout) / queue rejects (shed-only).
  assert brownout["failed"] == {} and shed_only["failed"] == {}
  assert sum(shed_only["queue_rejects"].values()) > 0
  # The trajectory proof: the ladder climbed under the ramp and the
  # recovery windows walked it back to L0 before the window closed.
  assert brownout["max_level"] >= 1
  assert brownout["returned_to_l0"] is True and out["returned_to_l0"]
  assert shed_only["max_level"] == 0  # the arm really ran unarmed
  assert brownout["interactive_p99_ms"] > 0
  # Attribution rode both arms: the ledger reconciled exactly against
  # the phase/request totals under concurrent load, and the cells name
  # real scenes (hottest first).
  for arm in (brownout, shed_only):
    attrib = arm["attrib"]
    assert attrib["conservation"]["ok"] is True
    assert attrib["cells_total"] >= 1
    assert attrib["top_cells"][0]["scene"].startswith("scene_")
    assert set(arm["device_seconds_by_class"]) == {
        "interactive", "prefetch", "background"}
    # The recorder ran in both arms even if dry scale fired no natural
    # alert; every capture it did make is indexed on disk.
    assert arm["incidents"]["captures"] == len(arm["incidents"]["index"])
  # The drill is the deterministic end-to-end pin: an induced latency
  # alert produced exactly one self-contained bundle.
  drill = out["incident_drill"]
  assert drill["captures"] >= 1
  assert drill["alert"]
  assert drill["attrib_cells"] >= 1
  assert drill["conservation_ok"] is True
  bundles = list((incident_dir / "drill").glob("incident-*.json"))
  assert len(bundles) >= 1
