"""bench/serve_load.py dry mode on CPU (subprocess) — tier-1 smoke.

Mirrors tests/test_bench_dry.py: the load generator's decision path
(service wiring, warm-up, closed-loop workers, JSON contract) is all
host+CPU-sized work, so guard rot in it is caught here rather than in a
TPU window. Asserts the single JSON line carries the serving headline
fields: renders_per_sec, p50_ms, p99_ms, cache_hit_rate.

The ``--chaos`` variant is the resilience layer's end-to-end smoke: a
seeded fault schedule injects transient errors and slow dispatches into
real closed-loop traffic, and the run must still complete with the
chaos accounting (injected counts, retries, breaker state) in the JSON.
"""

import json
import os
import subprocess
import sys

import pytest


def _run_dry(extra_args=()):
  repo = os.path.dirname(os.path.dirname(os.path.dirname(
      os.path.abspath(__file__))))
  sys.path.insert(0, repo)
  from _cpu_mesh import hardened_env

  env = hardened_env(1)
  env["SERVE_LOAD_DRY"] = "1"
  # Share the suite's persistent XLA cache so reruns skip the compiles.
  env["JAX_COMPILATION_CACHE_DIR"] = os.path.join(repo, ".jax_cache")
  proc = subprocess.run(
      [sys.executable, os.path.join(repo, "bench", "serve_load.py"),
       *extra_args],
      capture_output=True, text=True, timeout=1200, env=env, cwd=repo)
  assert proc.returncode == 0, (
      f"serve_load dry run failed:\n{proc.stderr[-3000:]}")
  return json.loads(proc.stdout.strip().splitlines()[-1])


@pytest.fixture(scope="module")
def traced_dry_run():
  """ONE ``--trace`` subprocess shared by the headline and trace smokes.

  The trace-enabled run is a strict superset of the plain one — same
  ``inprocess_run`` arc, same JSON contract, plus the ``trace`` block —
  and each dry run is a full JAX child-process spawn, the unit of cost
  in this file. Budget reclamation round 3: two spawns became one.
  """
  return _run_dry(["--trace"])


def test_serve_load_dry_emits_headline_json(traced_dry_run):
  out = traced_dry_run
  assert out["metric"] == "serve_load" and out["dry"] is True
  assert out["device"] == "cpu"
  assert out["renders_per_sec"] > 0
  assert out["p50_ms"] > 0 and out["p99_ms"] >= out["p50_ms"]
  assert 0 <= out["cache_hit_rate"] <= 1
  assert out["requests"] >= out["batches"] >= 1
  assert out["chaos"] is False
  # Pipeline accounting rides every run: the window, the device-idle
  # gap metric, out-of-order/abandoned counters, per-scene breakdown.
  assert out["inflight"] >= 1
  assert set(out["dispatch_gap"]) == {"count", "total_s", "mean_ms",
                                      "max_ms"}
  assert out["abandoned_batches"] == 0
  assert out["out_of_order_completions"] >= 0
  assert out["per_scene"]  # hot-scene breakdown present
  for entry in out["per_scene"].values():
    assert entry["requests"] > 0 and entry["p50_ms"] > 0
  # Outage accounting rides EVERY run (trend across BENCH rounds): the
  # error/resilience counters and breaker state, zeros and all.
  assert set(out["errors"]) == {"transient", "permanent", "deadline"}
  assert out["rejected"] == 0
  assert set(out["resilience"]) >= {"retries", "watchdog_trips",
                                    "fallback_renders", "breaker_opens"}
  assert out["breaker_state"] == "closed"
  # The SLO verdict block rides every run: objectives judged against
  # slow-window attainment. A clean dry run must PASS availability
  # outright (no errors => attainment 1.0).
  slo = out["slo"]
  assert set(slo["objectives"]) == {"availability", "latency",
                                    "latency_p99"}
  avail = slo["objectives"]["availability"]
  assert avail["target"] == 0.99 and avail["attained"] == 1.0
  assert avail["requests"] >= out["requests"]
  assert avail["pass"] is True and avail["burn_slow"] == 0.0
  assert slo["alerts_firing"] == []
  # The quantile-SLO verdict (flight recorder): p99 judged from the
  # pooled native histogram, percentile-true — the block must carry the
  # quantile, the threshold, and the measured window quantile. The
  # per-scene table rides along (bounded; every dry scene scored).
  q99 = slo["objectives"]["latency_p99"]
  assert q99["quantile"] == 0.99 and q99["threshold_ms"] == 1000.0
  assert q99["quantile_ms"] is not None and q99["quantile_ms"] > 0
  assert q99["requests"] >= out["requests"]
  assert q99["pass"] in (True, False)  # judged, not skipped
  per_scene = slo["per_scene"]
  assert per_scene["scenes"] >= 1
  assert isinstance(per_scene["failing"], list)
  # The attribution ledger rides every serve_load run: cells name the
  # dry scenes and the conservation invariant reconciles exactly even
  # under the closed-loop worker pool.
  attrib = out["attrib"]
  assert attrib["cells_total"] >= 1
  assert attrib["conservation"]["ok"] is True
  assert attrib["totals"]["requests"] >= out["requests"]
  assert attrib["top_cells"][0]["scene"].startswith("scene_")


def test_serve_load_trace_dry_smoke(traced_dry_run):
  """The trace-enabled smoke: closed-loop traffic under a live Tracer
  must finish, and the slowest-exemplar span trees must cover the whole
  request path (the acceptance span set + attempt children)."""
  out = traced_dry_run
  assert out["metric"] == "serve_load" and out["dry"] is True
  assert out["renders_per_sec"] > 0
  trace = out["trace"]
  assert trace["finished"] >= out["requests"]
  assert trace["slowest_ms"] and trace["slowest_ms"] > 0
  assert {"queue_wait", "batch_assembly", "dispatch", "attempt", "bake",
          "h2d", "compute", "readback"} <= set(trace["span_names"])


def test_serve_load_ab_dry_smoke():
  """The pipelined-vs-blocking A/B smoke: one process, two measured
  arms, one JSON line. Pins the contract (both arms' headline fields +
  the gap metric that proves/disproves device idle), NOT a dry-mode
  speedup — on 32-px toy scenes per-dispatch host overhead dominates
  and the win only shows at real sizes (recorded per BENCH round)."""
  out = _run_dry(["--ab"])
  assert out["metric"] == "serve_load_ab" and out["dry"] is True
  assert out["device"] == "cpu"
  assert out["speedup"] and out["speedup"] > 0
  pipelined, blocking = out["pipelined"], out["blocking"]
  assert pipelined["inflight"] >= 2 and blocking["inflight"] == 1
  for arm in (pipelined, blocking):
    assert arm["renders_per_sec"] > 0 and arm["p50_ms"] > 0
    assert set(arm["dispatch_gap"]) == {"count", "total_s", "mean_ms",
                                        "max_ms"}
  # Blocking serializes: every post-completion launch finds the device
  # idle, so its gap metric must have fired.
  assert blocking["dispatch_gap"]["count"] >= 1
  assert blocking["out_of_order_completions"] == 0


def test_serve_load_edge_ab_dry_smoke():
  """The edge-cache A/B smoke: Zipf-distributed poses served through the
  pose-quantized frame cache, then through the raw path, one JSON line.
  Pins the contract (both arms + hit/warp/miss split + p50 fields) and
  that the cache really served the bulk of the Zipf traffic — not a
  dry-mode p50 ordering, which toy scenes could flip on noise."""
  out = _run_dry(["--edge-ab", "--zipf-poses", "16"])
  assert out["metric"] == "serve_load_edge_ab" and out["dry"] is True
  assert out["device"] == "cpu" and out["zipf_poses"] == 16
  assert out["p50_ms_edge_on"] > 0 and out["p50_ms_edge_off"] > 0
  assert out["value"] and out["value"] > 0
  # The Zipf pool repeats poses, so the cache must have absorbed most
  # lookups (hits + warp serves), with the counts in the report.
  assert out["hits"] + out["warp_serves"] + out["misses"] > 0
  assert out["misses"] >= 1  # cells had to populate
  assert out["hit_rate"] > 0.5
  edge_on = out["edge_on"]
  assert edge_on["edge"]["hit_rate"] == out["hit_rate"]
  assert edge_on["requests"] > 0 and out["edge_off"]["requests"] > 0
  assert "edge" not in out["edge_off"]


def test_serve_load_tiled_ab_dry_smoke():
  """The tile-granular A/B smoke: one depth-stratified scene served
  through the tiled (frustum-culled) path and the monolithic path, one
  JSON line. Pins the contract — both arms' headline fields, the tile
  accounting (the pose pool MUST have culled tiles or the workload is
  broken), and the bit-exact full-coverage parity — NOT a dry-mode
  speedup: on 32-px toy scenes the per-request plan/concat overhead
  dominates and the render-cost win only shows at real sizes (recorded
  per BENCH round)."""
  # --duration 1: the contract (parity + cull accounting) needs poses
  # served, not a long window — tier-1 seconds are the scarce resource.
  out = _run_dry(["--tiled-ab", "--duration", "1"])
  assert out["metric"] == "serve_load_tiled_ab" and out["dry"] is True
  assert out["device"] == "cpu"
  # The pinned parity: the bench itself aborts (non-zero exit) when the
  # full-coverage pose is not bit-exact, so reaching here with the flag
  # set true is the end-to-end proof; the culled poses must stay at
  # float-rounding scale (conservative frustum + zero-padded sampling).
  assert out["parity"]["full_coverage_bit_exact"] is True
  assert out["parity"]["culled_pose_max_abs_diff"] <= 1e-4
  assert out["p50_ms_tiled"] > 0 and out["p50_ms_full"] > 0
  assert out["value"] and out["value"] > 0
  tiles = out["tiled"]["tiles"]
  assert tiles["tiled_requests"] > 0
  # The panning pose pool must actually exercise the cull: some tiles
  # culled, and the mean touched strictly inside (0, total).
  assert tiles["culled_total"] > 0
  assert 0 < out["tiles_touched_mean"] < out["tiles_total"]
  assert out["tiled"]["tile_cache"]["misses"] >= 1  # per-tile bakes ran
  assert out["full"]["requests"] > 0 and out["tiled"]["requests"] > 0
  assert "tiles" not in out["full"]


def test_serve_load_asset_ab_dry_smoke():
  """The asset delivery tier's tier-1 smoke: manifest + every tile
  asset over real HTTP (cold), full 304 revalidation (warm — the bench
  itself aborts if any conditional GET misses), a full cross-process
  SceneFetcher sync, and the quarter-scene diff re-sync. The PINNED
  acceptance number: diff-sync bytes strictly below both the full-sync
  bytes (the bench aborts otherwise) and the full-checkpoint bytes —
  tiles moved, not frames, not checkpoints."""
  out = _run_dry(["--asset-ab"])
  assert out["metric"] == "serve_load_asset_ab" and out["dry"] is True
  assert out["cold"]["assets"] == out["tiles_total"] >= 4
  assert out["cold"]["bytes"] > 0
  assert out["warm"]["not_modified"] == out["tiles_total"] + 1  # +manifest
  assert out["warm"]["bytes"] == 0  # 304s carry no bodies
  assert out["full_sync"]["tiles_fetched"] == out["tiles_total"]
  # The diff moved only the mutated quarter — and measurably fewer
  # bytes than shipping the scene as a checkpoint would.
  assert 0 < out["diff_sync"]["tiles_fetched"] < out["tiles_total"]
  assert out["diff_sync"]["bytes"] < out["full_sync"]["bytes"]
  assert out["diff_sync"]["bytes"] < out["full_checkpoint_bytes"]
  assert out["value"] == round(
      out["diff_sync"]["bytes"] / out["full_checkpoint_bytes"], 4)


def test_serve_load_cluster_dry_smoke():
  """The multi-host tier's tier-1 smoke: spawn real backend processes,
  route through the cluster Router, SIGKILL one backend mid-window, and
  the run must finish with failover + breaker isolation in the JSON."""
  out = _run_dry(["--cluster"])
  assert out["metric"] == "serve_load" and out["dry"] is True
  assert out["renders_per_sec"] > 0 and out["requests"] > 0
  cluster = out["cluster"]
  assert cluster["backends"] == 3 and cluster["replication"] == 2
  victim = cluster["killed"]
  assert victim is not None
  # The kill phase really happened and the fleet rode it out: requests
  # kept completing after the SIGKILL, attempts failed over to replicas,
  # and ONLY the dead backend's breaker opened.
  assert cluster["post_kill_requests"] > 0
  assert cluster["failovers"] >= 1
  assert cluster["breakers"][victim] == "open"
  for backend, state in cluster["breakers"].items():
    if backend != victim:
      assert state == "closed", f"healthy backend {backend} opened"
  assert cluster["health"] == "degraded"
  # Work landed on more than one backend: the ring really shards.
  assert len(cluster["forwards"]) >= 2
  # Fleet SLO view: the surviving backends report their slo blocks
  # through the router's aggregation, and the run carries the same
  # verdict shape as the in-process path.
  assert cluster["slo"]["backends_reporting"] >= 2
  if out["slo"] is not None:
    assert "availability" in out["slo"]["objectives"]


def test_serve_load_cluster_crashloop_dry_smoke():
  """The self-healing drill's tier-1 smoke: the fleet supervisor runs
  over the spawned pool, one backend is killed every time it comes back
  until its restart budget (1, for speed) quarantines it, and the JSON
  must record the whole arc — restarts, containment, and a fleet still
  serving after the quarantine."""
  out = _run_dry(["--cluster", "--chaos-crashloop", "--restart-budget", "1"])
  assert out["metric"] == "serve_load" and out["dry"] is True
  assert out["renders_per_sec"] > 0 and out["requests"] > 0
  cluster = out["cluster"]
  drill = cluster["crashloop"]
  victim = drill["victim"]
  # The supervisor really respawned the victim (budget's worth) and then
  # contained the loop: quarantined, no more restarts.
  assert drill["restarts"] == 1 and drill["restart_budget"] == 1
  assert drill["kills"] >= 2  # the respawned backend was killed again
  assert drill["quarantined"] is True
  assert drill["events"]["backend_restart"] >= 1
  assert drill["events"]["backend_quarantined"] == 1
  assert cluster["quarantines"] == {victim: 1}
  assert cluster["restarts"].get(victim, 0) >= 1
  assert victim in cluster["ejected"]
  # Post-quarantine the surviving replicas kept the fleet serving.
  assert drill["post_quarantine_requests"] > 0
  assert cluster["health"] == "degraded"


def test_serve_load_cluster_chaos_router_dry_smoke():
  """The router-HA drill's tier-1 smoke (ISSUE 15's acceptance pin):
  TWO gossiping router replicas front the pool, closed-loop clients
  hammer the SURVIVOR, and the supervising router is SIGKILLed
  mid-window. The run must record zero failed requests on the survivor,
  a bounded lease takeover, and a backend killed AFTER the takeover
  respawned by the new leader through the --restart-hook webhook."""
  out = _run_dry(["--cluster", "--chaos-router"])
  assert out["metric"] == "serve_load" and out["dry"] is True
  assert out["renders_per_sec"] > 0 and out["requests"] > 0
  cluster = out["cluster"]
  assert cluster["backends"] == 3 and cluster["replication"] == 2
  # THE pin: the survivor dropped nothing — before, during, or after
  # the router kill (failure_counts is empty, not merely small).
  assert cluster["failed_requests"] == {}
  assert cluster["post_kill_requests"] > 0
  drill = cluster["chaos_router"]
  assert drill["routers"] == 2
  assert drill["killed_router"] == "routerA"
  assert drill["survivor"] == "routerB"
  # Supervision moved: the survivor reaped the stale lease in bounded
  # time and its own metrics agree it now leads.
  assert drill["lease_taken_over"] is True
  assert drill["takeover_s"] is not None
  assert drill["takeovers_total"] >= 1
  assert drill["lease_held"] == 1
  assert drill["lease_owner"] == "routerB"
  # A backend killed AFTER the takeover was respawned by the NEW
  # leader, via the restart webhook — remote supervision really works.
  assert drill["backend_killed"] is not None
  assert drill["backend_respawned"] is True
  assert drill["respawn_s"] is not None
  assert drill["hook_invocations"] >= 1
  assert drill["hook_failures"] == 0
  # Anti-entropy really ran between the replicas.
  assert drill["gossip"]["rounds"] > 0


def test_serve_load_chaos_dry_smoke():
  """Chaos mode must inject faults AND finish healthy: the workload rides
  retries/fallback instead of aborting, and the JSON carries the
  resilience accounting."""
  out = _run_dry(["--chaos"])
  assert out["metric"] == "serve_load" and out["dry"] is True
  assert out["chaos"] is True
  assert out["renders_per_sec"] > 0 and out["requests"] > 0
  injected = out["chaos_injected"]
  assert injected["error"] > 0  # the schedule really fired
  # Injected transient faults surface as retries (and possibly breaker
  # opens), not as aborted runs.
  assert out["resilience"]["retries"] > 0
  assert out["breaker_state"] in ("closed", "open", "half_open")
  assert set(out["errors"]) == {"transient", "permanent", "deadline"}
  assert out["chaos_failed_requests"] is not None
  # The verdict block judges the chaos window too (objective, attained,
  # burn rates, pass/fail — whether the fleet RODE OUT the faults).
  # Quantile objectives are scored by their windowed quantile instead of
  # a fractional attainment.
  slo = out["slo"]
  for obj in slo["objectives"].values():
    if "quantile" in obj:
      assert {"quantile", "threshold_ms", "quantile_ms", "burn_fast",
              "burn_slow", "pass"} <= set(obj)
    else:
      assert {"target", "attained", "burn_fast", "burn_slow",
              "pass"} <= set(obj)
  assert slo["objectives"]["availability"]["requests"] >= out["requests"]


def test_serve_load_overload_ab_dry_smoke(tmp_path):
  """The brownout A/B's tier-1 smoke: one process, a ~3x phased
  overload ramp driven twice — ladder armed, then shed-only — and one
  JSON line. Dry scale pins MECHANICS only (same contract as the --ab
  and --tiled-ab dry smokes, where toy-scene verdicts are noise): the
  ladder engages under the ramp and recovers to L0, interactive is
  never shed below L4, neither arm 5xxs, and the JSON carries the full
  acceptance shape. The performance verdict — brownout buys
  interactive goodput and holds the SLO that shed-only violates —
  belongs to real sizes (`--overload-ab --duration 10`, BENCH-style).

  With --incident-dir this smoke also rides the incident-lens arc
  (PR 18): both arms carry an attribution block whose conservation
  invariant holds through real multithreaded load, the per-class
  device-seconds split is computed, and the deterministic incident
  drill captures exactly the induced bundle end-to-end — alert edge ->
  black-box file on disk — without a second subprocess."""
  out = _run_dry(["--overload-ab", "--incident-dir",
                  str(tmp_path / "bb")])
  assert out["metric"] == "serve_load_overload_ab" and out["dry"] is True
  assert out["latency_threshold_ms"] > 0  # calibrated, not hardcoded
  brownout, shed_only = out["brownout"], out["shed_only"]
  # Shape: the goodput ratio and verdicts are computed and sane, even
  # though dry scale can't pin which way they fall.
  assert out["interactive_goodput_x"] is not None
  assert out["interactive_goodput_x"] > 0
  assert isinstance(brownout["slo"]["pass"], bool)
  assert isinstance(shed_only["slo"]["pass"], bool)
  # Admission contract: interactive is shed ONLY at L4 — if the ladder
  # never maxed out, interactive sheds must be exactly zero.
  if brownout["max_level"] < 4:
    assert brownout["sheds"]["interactive"] == 0
  assert brownout["requests_ok"]["interactive"] > 0
  # No 5xx storm in either arm: failures stay empty, pressure resolves
  # as sheds (brownout) / queue rejects (shed-only).
  assert brownout["failed"] == {} and shed_only["failed"] == {}
  assert sum(shed_only["queue_rejects"].values()) > 0
  # The trajectory proof: the ladder climbed under the ramp and the
  # recovery windows walked it back to L0 before the window closed.
  assert brownout["max_level"] >= 1
  assert brownout["returned_to_l0"] is True and out["returned_to_l0"]
  assert shed_only["max_level"] == 0  # the arm really ran unarmed
  assert brownout["interactive_p99_ms"] > 0
  # Attribution rode both arms: the ledger reconciled exactly against
  # the phase/request totals under concurrent load, and the cells name
  # real scenes (hottest first).
  for arm in (brownout, shed_only):
    attrib = arm["attrib"]
    assert attrib["conservation"]["ok"] is True
    assert attrib["cells_total"] >= 1
    assert attrib["top_cells"][0]["scene"].startswith("scene_")
    assert set(arm["device_seconds_by_class"]) == {
        "interactive", "prefetch", "background"}
    # The recorder ran in both arms even if dry scale fired no natural
    # alert; every capture it did make is indexed on disk.
    assert arm["incidents"]["captures"] == len(arm["incidents"]["index"])
  # The drill is the deterministic end-to-end pin: an induced latency
  # alert produced exactly one self-contained bundle.
  drill = out["incident_drill"]
  assert drill["captures"] >= 1
  assert drill["alert"]
  assert drill["attrib_cells"] >= 1
  assert drill["conservation_ok"] is True
  bundles = list((tmp_path / "bb" / "drill").glob("incident-*.json"))
  assert len(bundles) >= 1
