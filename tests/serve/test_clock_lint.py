"""Lint: serve/ and obs/ read time only through injectable clocks.

Every latency, deadline, and span edge in the serving stack must come
from a clock the caller can inject — that is what makes the breaker,
scheduler, tracer, and metrics deterministic in tier-1 (fake clocks)
and keeps all timestamps on ONE base in production. A bare
``time.time()`` / ``time.monotonic()`` / ``time.perf_counter()`` call
creeping into a hot path silently breaks both, so this test greps the
source.

Designated defaults stay legal: ``clock=time.monotonic`` in a signature
or ``clock if clock else time.monotonic`` pass the *function object* —
only call sites (with parentheses) are flagged. ``time.sleep`` is a
different contract (injected separately where determinism needs it) and
is not a clock read.
"""

import pathlib
import re

import mpi_vision_tpu.obs
import mpi_vision_tpu.serve

_CLOCK_CALL = re.compile(r"\btime\.(time|monotonic|perf_counter)\s*\(")


def _package_sources(pkg):
  root = pathlib.Path(pkg.__file__).parent
  return sorted(root.glob("*.py"))


def test_no_bare_clock_calls_in_serve_and_obs():
  offenders = []
  for pkg in (mpi_vision_tpu.serve, mpi_vision_tpu.obs):
    for path in _package_sources(pkg):
      for lineno, line in enumerate(path.read_text().splitlines(), 1):
        code = line.split("#", 1)[0]
        if _CLOCK_CALL.search(code):
          offenders.append(f"{path.name}:{lineno}: {line.strip()}")
  assert not offenders, (
      "bare clock calls in serve/obs hot paths (inject a clock instead; "
      "attribute references like clock=time.monotonic are fine):\n"
      + "\n".join(offenders))


def test_lint_actually_catches_calls():
  # The regex must flag real call sites, not just pass everything.
  assert _CLOCK_CALL.search("t0 = time.monotonic()")
  assert _CLOCK_CALL.search("x = time.perf_counter ()")
  assert not _CLOCK_CALL.search("clock=time.monotonic")
  assert not _CLOCK_CALL.search("sleep = time.sleep")
