"""Lint: serve/ (cluster/ included), obs/, ckpt/, and the hardened train
loop read time only through injectable clocks.

The PR-7 streaming rebuild (serve/engine.py + serve/scheduler.py, the
ckpt background saver) is explicitly in the coverage self-check below —
the pipeline's gap/latency/deadline math all rides the injected clocks.

Every latency, deadline, span edge, stall measurement, and manifest
timestamp must come from a clock the caller can inject — that is what
makes the breaker, scheduler, tracer, metrics, checkpoint store, and
stall watchdog deterministic in tier-1 (fake clocks) and keeps all
timestamps on ONE base in production. A bare ``time.time()`` /
``time.monotonic()`` / ``time.perf_counter()`` call creeping into a hot
path silently breaks both, so this test greps the source — the whole
``serve``/``obs``/``ckpt`` packages plus ``train/loop.py`` (the
crash-safe ``fit_resumable`` path; the notebook-parity helpers around it
ride along for free).

Designated defaults stay legal: ``clock=time.monotonic`` in a signature
or ``clock if clock else time.monotonic`` pass the *function object* —
only call sites (with parentheses) are flagged. ``time.sleep`` is a
different contract (injected separately where determinism needs it) and
is not a clock read.
"""

import pathlib
import re

import mpi_vision_tpu.ckpt
import mpi_vision_tpu.obs
import mpi_vision_tpu.serve
import mpi_vision_tpu.serve.assets
import mpi_vision_tpu.serve.cluster
import mpi_vision_tpu.serve.edge
import mpi_vision_tpu.serve.session
import mpi_vision_tpu.train.faultinject
import mpi_vision_tpu.train.loop
import mpi_vision_tpu.train.queue
import mpi_vision_tpu.train.supervisor
import mpi_vision_tpu.train.telemetry

_CLOCK_CALL = re.compile(r"\btime\.(time|monotonic|perf_counter)\s*\(")


def _package_sources(pkg):
  root = pathlib.Path(pkg.__file__).parent
  return sorted(root.glob("*.py"))


def _linted_sources():
  for pkg in (mpi_vision_tpu.serve, mpi_vision_tpu.serve.assets,
              mpi_vision_tpu.serve.cluster, mpi_vision_tpu.serve.edge,
              mpi_vision_tpu.serve.session,
              mpi_vision_tpu.obs, mpi_vision_tpu.ckpt):
    yield from _package_sources(pkg)
  yield pathlib.Path(mpi_vision_tpu.train.loop.__file__)
  yield pathlib.Path(mpi_vision_tpu.train.telemetry.__file__)
  # The training queue tier (PR 12): lease timestamps, retry backoff
  # floors, wedge/grace windows — all injected-clock territory.
  yield pathlib.Path(mpi_vision_tpu.train.queue.__file__)
  yield pathlib.Path(mpi_vision_tpu.train.supervisor.__file__)
  yield pathlib.Path(mpi_vision_tpu.train.faultinject.__file__)


def test_no_bare_clock_calls_in_serve_obs_ckpt_train():
  offenders = []
  for path in _linted_sources():
    for lineno, line in enumerate(path.read_text().splitlines(), 1):
      code = line.split("#", 1)[0]
      if _CLOCK_CALL.search(code):
        offenders.append(f"{path.name}:{lineno}: {line.strip()}")
  assert not offenders, (
      "bare clock calls in serve/obs/ckpt/train-loop hot paths (inject a "
      "clock instead; attribute references like clock=time.monotonic are "
      "fine):\n" + "\n".join(offenders))


def test_lint_covers_the_ckpt_package_and_train_loop():
  # Package-qualified so e.g. serve/faultinject.py can never satisfy a
  # check meant for ckpt/faultinject.py. If these move, re-point the
  # lint — silently shrinking coverage is exactly the failure mode this
  # test exists to prevent.
  rel = {"/".join(p.parts[-2:]) for p in _linted_sources()}
  assert {"ckpt/store.py", "ckpt/guards.py", "ckpt/faultinject.py",
          "ckpt/watch.py", "ckpt/background.py", "serve/faultinject.py",
          "serve/engine.py", "serve/scheduler.py", "serve/metrics.py",
          # The tile tier (PR 13): the planner is request-path code and
          # the tile/crop caches feed the latency accounting.
          "serve/tiles.py", "serve/cache.py", "serve/server.py",
          # The brownout tier (PR 17): dwell and recovery windows are
          # the hysteresis — one bare clock call makes the ladder
          # untestable and ties descent cadence to wall time.
          "serve/brownout.py",
          "train/loop.py", "train/telemetry.py", "train/queue.py",
          "train/supervisor.py", "train/faultinject.py",
          "cluster/router.py",
          "cluster/ring.py", "cluster/pool.py", "cluster/supervisor.py",
          # The router-HA tier (PR 15): gossip versions and lease
          # heartbeats ARE timestamps — one bare clock call desyncs
          # the anti-entropy merge from the takeover math.
          "cluster/gossip.py", "cluster/lease.py",
          # The elastic fleet (PR 19): sustain windows, cooldowns, and
          # the scaling budget ARE the anti-flap guarantees — a bare
          # clock call would weld them to wall time.
          "cluster/autoscale.py",
          # The asset tier (PR 16): sync sweep timing and watcher polls
          # ride the same injected clocks as the checkpoint watcher.
          "assets/store.py", "assets/fetch.py",
          "edge/cache.py", "edge/lattice.py", "edge/warp.py",
          # The session tier (PR 20): idle reaping and frame deadlines
          # ride the manager's injectable clock — a bare call would
          # make reap tests flaky and weld idle timeouts to wall time.
          "session/manager.py", "session/protocol.py",
          "session/predictor.py",
          "obs/slo.py", "obs/events.py", "obs/trace.py",
          "obs/prom.py", "obs/hist.py", "obs/tsdb.py",
          "obs/ship.py",
          # The incident lens (PR 18): the attribution ledger stamps
          # queue-wait and device seconds, and the recorder timestamps
          # bundles — bare clock calls would make conservation and
          # capture dedup untestable.
          "obs/attrib.py", "obs/incident.py"} <= rel


def test_lint_actually_catches_calls():
  # The regex must flag real call sites, not just pass everything.
  assert _CLOCK_CALL.search("t0 = time.monotonic()")
  assert _CLOCK_CALL.search("x = time.perf_counter ()")
  assert not _CLOCK_CALL.search("clock=time.monotonic")
  assert not _CLOCK_CALL.search("sleep = time.sleep")
