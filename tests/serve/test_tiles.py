"""Tile-granular scenes (serve/tiles.py) end to end.

The acceptance pins from the tiling issue live here:

  (1) **bit-exact full-frustum parity** — a pose whose frustum touches
      every tile renders bit-identically through the tiled service and
      the monolithic one (the crop is the whole scene, the correction
      is skipped, the jit signature is shared);
  (2) **conservative culling** — a pose whose frustum touches a strict
      subset of tiles renders the same pixels to float-rounding scale
      (the cropped sampler taps the same source pixels; out-of-crop
      taps were zero-padded either way);
  (3) **partial reload** — a live ``swap_scenes`` where ONE tile's
      bytes changed swaps only that tile: untouched tiles keep their
      baked cache entries (same resident objects), and edge frames
      that never sampled a changed tile survive WITH their strong
      ETags (revalidation still answers 304);
  (4) **tile-granular placement** — ``(scene, tile)`` ring keys are
      deterministic, spread one scene over many backends, and a ring
      resize moves only the keys the new backend actually takes.

Scene geometry: 16x16, 4 planes, tile 8 (a 2x2 grid) with a narrow-FOV
camera (fx = 2w), so a ±0.35 rad pan views ONE tile column — small
enough that every compile is toy-sized, structured enough that culling,
plane masks, and tile-addressed invalidation all engage.
"""

import math

import numpy as np
import pytest

from mpi_vision_tpu.core import camera
from mpi_vision_tpu.core.sampling import Convention
from mpi_vision_tpu.serve import RenderService
from mpi_vision_tpu.serve import cache as cache_mod
from mpi_vision_tpu.serve import tiles as tiles_mod
from mpi_vision_tpu.serve.cluster.ring import HashRing
from mpi_vision_tpu.serve.edge import EdgeConfig
from mpi_vision_tpu.serve.server import synthetic_tiled_scene

H = W = 16
P = 4
TILE = 8  # 2x2 grid


def _scene(seed=0):
  layers, depths, _ = synthetic_tiled_scene(
      "s", height=H, width=W, planes=P, regions=2, seed=seed)
  # Narrow FOV (fx = 2w): a +-0.35 rad pan shifts taps by ~0.73w, so
  # the frustum walks off one tile column entirely (margin included).
  k = np.asarray(camera.intrinsics_matrix(2.0 * W, 2.0 * W, W / 2.0,
                                          H / 2.0), np.float32)
  return layers, depths, k


def _pan(theta):
  c, s = math.cos(theta), math.sin(theta)
  pose = np.eye(4, dtype=np.float32)
  pose[0, 0], pose[0, 2], pose[2, 0], pose[2, 2] = c, s, -s, c
  return pose


# Frustum shapes the module's tests share: identity touches everything;
# the pans each view one tile column of the 2x2 grid.
POSE_FULL = np.eye(4, dtype=np.float32)
POSE_RIGHT = _pan(-0.35)  # views the right tile column only
POSE_LEFT = _pan(0.35)    # views the left tile column only


# --- TileGrid / TileSignature / TileMeta (host-side, no engine) ----------


def test_tile_grid_rect_and_ragged_edges():
  grid = tiles_mod.TileGrid(20, 16, 8)  # ragged last row
  assert (grid.rows, grid.cols, len(grid)) == (3, 2, 6)
  assert grid.rect(0, 0) == (0, 8, 0, 8)
  assert grid.rect(2, 1) == (16, 20, 8, 16)  # clipped to the scene
  with pytest.raises(ValueError):
    tiles_mod.TileGrid(16, 16, 0)


def test_signature_token_round_trips():
  layers, depths, k = _scene()
  meta = tiles_mod.TileMeta.build(layers, depths, k, TILE)
  for pose in (POSE_FULL, POSE_RIGHT, POSE_LEFT):
    sig = meta.plan(pose[None])
    back = tiles_mod.TileSignature.parse(sig.token(), meta.grid)
    assert back == sig


def test_frustum_cull_marks_one_column_for_a_narrow_pan():
  layers, depths, k = _scene()
  meta = tiles_mod.TileMeta.build(layers, depths, k, TILE)
  assert meta.touched(POSE_FULL[None]).all()
  right = meta.touched(POSE_RIGHT[None])
  left = meta.touched(POSE_LEFT[None])
  # Each pan sees exactly one tile column; between them they disagree
  # on every column, which is what the partial-reload pins rely on.
  assert right[:, 1].all() and not right[:, 0].any()
  assert left[:, 0].all() and not left[:, 1].any()
  # The signature's crop snaps to the touched column.
  assert meta.signature(right).crop == (0, H, TILE, W)
  assert meta.signature(left).crop == (0, H, 0, TILE)


def test_changed_tiles_diffs_per_tile_and_geometry_changes_all():
  layers, depths, k = _scene()
  meta = tiles_mod.TileMeta.build(layers, depths, k, TILE)
  same = tiles_mod.TileMeta.build(layers.copy(), depths, k, TILE)
  assert meta.changed_tiles(same) == []
  touched = layers.copy()
  touched[0:TILE, TILE:W, :, :3] += 0.125  # tile (0, 1) rgb only
  assert meta.changed_tiles(
      tiles_mod.TileMeta.build(touched, depths, k, TILE)) == [(0, 1)]
  # A geometry change (intrinsics) retires every tile id AND changes
  # the scene digest (the _edge_put swap-race guard must refuse frames
  # rendered with the old camera even when no pixel byte moved).
  k2 = k.copy()
  k2[0, 0] *= 2.0
  geo = tiles_mod.TileMeta.build(layers, depths, k2, TILE)
  assert len(meta.changed_tiles(geo)) == 4
  assert geo.scene_digest != meta.scene_digest
  assert same.scene_digest == meta.scene_digest


def test_ragged_sliver_crop_pulls_in_a_neighbor_tile():
  # A 20px-tall scene with tile 8 has a 4px ragged last row; a frustum
  # touching ONLY that row must not produce a 4px crop (the REF
  # conventions' tap affine degenerates below ~2px and bookkeeping
  # below 8) — the signature widens into the neighboring tile row.
  layers = np.zeros((20, 16, P, 4), np.float32)
  layers[..., 3] = 1.0
  depths = np.linspace(10.0, 1.0, P).astype(np.float32)
  k = np.asarray(camera.intrinsics_matrix(32.0, 32.0, 8.0, 10.0),
                 np.float32)
  meta = tiles_mod.TileMeta.build(layers, depths, k, 8)
  touched = np.zeros((meta.grid.rows, meta.grid.cols), bool)
  touched[2, :] = True  # the ragged 4px row only
  sig = meta.signature(touched)
  y0, y1, x0, x1 = sig.crop
  assert y1 - y0 >= 8 and (y0, y1) == (8, 20)
  assert sig.tiles_rendered == 4  # both rows of the widened crop
  # Round-trips through the batch key like any other signature.
  assert tiles_mod.TileSignature.parse(sig.token(), meta.grid) == sig


def test_per_tile_depth_range_follows_content():
  layers, depths, k = _scene()
  layers = layers.copy()
  # Tile (0, 0): content only on plane 2 (plus the 1-px neighbour
  # dilation band, silenced here by zeroing a 1-px halo too).
  layers[:TILE + 1, :TILE + 1, :, 3] = 0.0
  layers[:TILE - 1, :TILE - 1, 2, 3] = 1.0
  meta = tiles_mod.TileMeta.build(layers, depths, k, TILE)
  lo, hi = meta.depth_range(0, 0)
  assert lo == hi == float(depths[2])
  layers[:TILE + 1, :TILE + 1, :, 3] = 0.0
  meta2 = tiles_mod.TileMeta.build(layers, depths, k, TILE)
  assert meta2.depth_range(0, 0) is None  # empty tile


# --- tile-granular ring placement ----------------------------------------


TILES_6X6 = [(i, j) for i in range(6) for j in range(6)]


def test_tile_placement_deterministic_and_spreads_one_scene():
  a = HashRing(["x", "y", "z"], replication=2)
  b = HashRing(["z", "x", "y"], replication=2)  # insertion order differs
  for t in TILES_6X6:
    assert a.placement("hot", tile=t) == b.placement("hot", tile=t)
    assert len(set(a.placement("hot", tile=t))) == 2
  # The point of (scene, tile) keys: ONE hot scene's tiles land on
  # every backend instead of pinning the scene-level primary.
  assert {a.primary("hot", tile=t) for t in TILES_6X6} == {"x", "y", "z"}
  # Tile keys cannot collide with scene-level keys by construction.
  assert a.placement_key("hot", (1, 2)) != a.placement_key("hot")


def test_tile_placement_on_ring_resize_moves_only_the_taken_keys():
  before = HashRing(["a", "b", "c"], replication=2)
  grown = HashRing(["a", "b", "c", "d"], replication=2)
  moved = 0
  for t in TILES_6X6:
    if "d" not in grown.placement("hot", tile=t):
      assert grown.placement("hot", tile=t) == before.placement("hot",
                                                                tile=t)
    else:
      moved += 1
  assert 0 < moved < len(TILES_6X6)  # d took some tiles, not the scene
  shrunk = HashRing(["a", "b", "c", "d"], replication=2)
  shrunk.remove("d")
  for t in TILES_6X6:
    assert shrunk.placement("hot", tile=t) == before.placement("hot",
                                                               tile=t)


# --- tile LRU byte accounting --------------------------------------------


def _fake_tile(key: str, nbytes: int) -> cache_mod.BakedScene:
  return cache_mod.BakedScene(key, rgba_layers=None, depths=None,
                              intrinsics=None, nbytes=nbytes)


def test_tile_lru_accounts_and_evicts_per_tile():
  cache = cache_mod.SceneCache(byte_budget=300)
  for j in range(3):
    key = tiles_mod.tile_cache_key("s", 0, j)
    cache.get_or_bake(key, lambda k=key: _fake_tile(k, 100))
  stats = cache.stats()
  assert stats["bytes"] == 300 and stats["scenes"] == 3
  # One more tile: the LRU (tile 0,0) is evicted, bytes stay exact.
  cache.get_or_bake(tiles_mod.tile_cache_key("s", 0, 3),
                    lambda: _fake_tile(tiles_mod.tile_cache_key("s", 0, 3),
                                       100))
  stats = cache.stats()
  assert stats["bytes"] == 300 and stats["evictions"] == 1
  assert cache.get(tiles_mod.tile_cache_key("s", 0, 0)) is None
  assert cache.get(tiles_mod.tile_cache_key("s", 0, 1)) is not None
  # Per-tile invalidation subtracts exactly that tile's bytes...
  assert cache.invalidate(tiles_mod.tile_cache_key("s", 0, 1))
  assert cache.stats()["bytes"] == 200
  # ...and the prefix sweep (grid-changing reloads) drops the rest of
  # the scene's tiles without touching other scenes.
  cache.get_or_bake("other", lambda: _fake_tile("other", 50))
  assert cache.invalidate_prefix("s" + tiles_mod.KEY_SEP) == 2
  stats = cache.stats()
  assert stats["bytes"] == 50 and stats["scenes"] == 1


# --- the tiled service: parity, batching, partial reload -----------------


@pytest.fixture(scope="module")
def scene_data():
  return _scene(seed=3)


@pytest.fixture(scope="module")
def tiled_svc(scene_data):
  layers, depths, k = scene_data
  service = RenderService(
      max_batch=2, max_wait_ms=2.0, use_mesh=False, tile=TILE,
      edge=EdgeConfig(trans_cell=0.02, rot_bucket_deg=2.0,
                      byte_budget=64 << 20))
  service.add_scene("s", layers, depths, k)
  yield service
  service.close()


@pytest.fixture(scope="module")
def mono_svc(scene_data):
  layers, depths, k = scene_data
  service = RenderService(max_batch=2, max_wait_ms=2.0, use_mesh=False)
  service.add_scene("s", layers, depths, k)
  yield service
  service.close()


def test_full_frustum_render_is_bit_exact(tiled_svc, mono_svc):
  # The identity pose touches every tile and keeps every plane: the
  # assembled crop IS the scene, no correction is applied, and the
  # render must be bit-identical to the monolithic path.
  tiled = tiled_svc.render("s", POSE_FULL, timeout=60)
  mono = mono_svc.render("s", POSE_FULL, timeout=60)
  assert tiled.tobytes() == mono.tobytes()
  tiles = tiled_svc.stats()["tiles"]
  assert tiles["tiled_requests"] >= 1
  assert tiles["touched_total"] >= 4  # all four tiles counted


def test_culled_render_matches_to_float_rounding(tiled_svc, mono_svc):
  # A one-column frustum renders a genuine crop (half the pixels, the
  # column's plane set); the sampler taps the same source pixels, so
  # the only daylight vs the monolithic render is float rounding in
  # the crop-corrected homography chain.
  for pose in (POSE_RIGHT, POSE_LEFT):
    tiled = tiled_svc.render("s", pose, timeout=60)
    mono = mono_svc.render("s", pose, timeout=60)
    assert tiled.shape == mono.shape  # full target frame either way
    assert float(np.abs(tiled - mono).max()) <= 1e-4
  tiles = tiled_svc.stats()["tiles"]
  assert tiles["culled_total"] >= 4  # two tiles culled per pan pose
  # The culled plans really were smaller: the per-tile cache baked
  # tiles, and the crop memo holds distinct per-signature crops.
  assert tiled_svc.stats()["tile_cache"]["misses"] >= 2


def test_unknown_scene_404_contract_survives_tiling(tiled_svc):
  with pytest.raises(KeyError):
    tiled_svc.render("nope", POSE_FULL, timeout=60)
  with pytest.raises(KeyError):
    tiled_svc.render_edge("nope", POSE_FULL, timeout=60)


def test_tiled_service_guards(scene_data):
  layers, depths, k = scene_data
  # fused_pallas cannot render cropped sources: fail at construction,
  # not as per-request 500s on the first culled pose.
  with pytest.raises(ValueError, match="XLA method"):
    RenderService(tile=TILE, method="fused_pallas", use_mesh=False)
  with pytest.raises(ValueError, match="tile must be >= 8"):
    RenderService(tile=4, use_mesh=False)
  # The key separator can never become part of a scene id.
  svc = RenderService(max_batch=2, use_mesh=False, tile=TILE)
  try:
    with pytest.raises(ValueError, match="x1f"):
      svc.add_scene("s" + tiles_mod.KEY_SEP + "t0,0", layers, depths, k)
  finally:
    svc.close()


def test_partial_reload_swaps_only_the_changed_tile(scene_data):
  layers, depths, k = scene_data
  svc = RenderService(
      max_batch=2, max_wait_ms=2.0, use_mesh=False, tile=TILE,
      edge=EdgeConfig(trans_cell=0.02, rot_bucket_deg=2.0,
                      byte_budget=64 << 20))
  svc.add_scene("s", layers, depths, k)
  try:
    # Populate: every tile baked, one edge frame per frustum shape.
    _, info_full = svc.render_edge("s", POSE_FULL, timeout=60)
    left_img, info_left = svc.render_edge("s", POSE_LEFT, timeout=60)
    _, info_right = svc.render_edge("s", POSE_RIGHT, timeout=60)
    assert info_left["etag"] and info_right["etag"]
    resident_before = {key: entry for key, entry
                       in svc._tile_cache._scenes.items()}
    assert len(resident_before) == 4

    # Live reload where ONE tile's bytes changed: tile (0, 1) — the
    # right column POSE_RIGHT sampled and POSE_LEFT provably did not.
    changed = layers.copy()
    changed[0:TILE, TILE:W, :, :3] = np.clip(
        changed[0:TILE, TILE:W, :, :3] + 0.125, 0.0, 1.0)
    svc.swap_scenes({"s": (changed, depths, k)}, prebake=False)

    # The baked-tile cache swapped ONLY tile (0, 1): the other three
    # entries are the SAME resident objects, byte accounting intact.
    after = dict(svc._tile_cache._scenes)
    changed_key = tiles_mod.tile_cache_key("s", 0, 1)
    assert changed_key not in after  # re-bakes lazily on next touch
    for key, entry in after.items():
      assert entry is resident_before[key]
    assert svc._tile_cache.stats()["invalidations"] == 1

    # Edge tier: the left-column frame never sampled the changed tile,
    # so it survives WITH its strong ETag — revalidation still answers
    # 304 — while the full-coverage and right-column frames (both
    # sampled it) are gone, and a fresh right render shows new pixels.
    assert svc.edge_revalidate("s", POSE_LEFT,
                          if_none_match=info_left["etag"]) is not None
    assert svc.edge_revalidate("s", POSE_RIGHT,
                          if_none_match=info_right["etag"]) is None
    assert svc.edge_revalidate("s", POSE_FULL,
                          if_none_match=info_full["etag"]) is None
    img_left2, info_left2 = svc.render_edge("s", POSE_LEFT, timeout=60)
    assert info_left2["edge"] == "hit"
    assert info_left2["etag"] == info_left["etag"]
    assert img_left2.tobytes() == left_img.tobytes()
    _, info_right2 = svc.render_edge("s", POSE_RIGHT, timeout=60)
    assert info_right2["edge"] == "miss"
    assert info_right2["etag"] != info_right["etag"]

    # A no-op swap (identical bytes) invalidates nothing at all.
    svc.swap_scenes({"s": (changed, depths, k)}, prebake=False)
    assert svc._tile_cache.stats()["invalidations"] == 1
    assert svc.edge_revalidate("s", POSE_LEFT,
                          if_none_match=info_left["etag"]) is not None
  finally:
    svc.close()


def test_swap_event_carries_per_scene_tiles_changed(scene_data):
  layers, depths, k = scene_data
  svc = RenderService(max_batch=2, max_wait_ms=2.0, use_mesh=False,
                      tile=TILE)
  svc.add_scene("s", layers, depths, k)
  try:
    changed = layers.copy()
    changed[0:TILE, 0:TILE, :, :3] = np.clip(
        changed[0:TILE, 0:TILE, :, :3] + 0.25, 0.0, 1.0)
    svc.swap_scenes({"s": (changed, depths, k)}, prebake=False)
    swaps = svc.events.snapshot(kind="scene_swap")["events"]
    assert swaps and swaps[-1]["tiles_changed"] == {"s": 1}
  finally:
    svc.close()


def test_tiled_service_plays_with_exact_convention(scene_data):
  # Non-square-safe path: the planner must reproduce whatever
  # convention the engine renders with (EXACT here), full coverage
  # staying bit-exact against a monolithic EXACT service.
  layers, depths, k = scene_data
  svc_t = RenderService(max_batch=2, max_wait_ms=2.0, use_mesh=False,
                        tile=TILE, convention=Convention.EXACT)
  svc_m = RenderService(max_batch=2, max_wait_ms=2.0, use_mesh=False,
                        convention=Convention.EXACT)
  svc_t.add_scene("s", layers, depths, k)
  svc_m.add_scene("s", layers, depths, k)
  try:
    assert svc_t.render("s", POSE_FULL, timeout=60).tobytes() == \
        svc_m.render("s", POSE_FULL, timeout=60).tobytes()
    assert float(np.abs(svc_t.render("s", POSE_RIGHT, timeout=60)
                        - svc_m.render("s", POSE_RIGHT,
                                       timeout=60)).max()) <= 1e-4
  finally:
    svc_t.close()
    svc_m.close()
