"""Elastic fleet: policy state machine, actuator choreography, takeover
convergence, and the live scale-out/scale-in acceptance arc.

Four layers, cheapest first:

  * ``AutoscalePolicy`` on a fake clock — every trip/recover band,
    sustain window, cooldown, budget gate, and min/max clamp is pinned
    deterministically in milliseconds of real time.
  * ``Autoscaler`` over fakes + a real ``Router`` — warm-BEFORE-admit
    ordering, the provision-hook spawn path, abort-and-retire on
    un-warmable capacity, eject-before-SIGTERM drainless retirement,
    and quarantine-aware victim selection.
  * The leaseholder-death drill — a supervisor takes over a gossiped
    half-finished scale-out and either completes the admit or retires
    the stranded spawn, with the dead leader's quarantine verdict and
    budget spends intact (ISSUE 19's convergence pin).
  * ONE live acceptance arc on the shared session pool: ramp ->
    real 4th-backend spawn with warmed admit -> drainless retire back
    to 3, with closed-loop clients seeing ZERO failed requests.
"""

import json
import signal
import sys
import threading
import time

import numpy as np
import pytest

from mpi_vision_tpu.serve.assets.fetch import warm_backend
from mpi_vision_tpu.serve.cluster import (
    Autoscaler,
    AutoscaleConfig,
    AutoscalePolicy,
    FleetSupervisor,
    GossipState,
    Router,
)
from mpi_vision_tpu.serve.cluster.autoscale import AUTOSCALE_KEY


class FakeClock:
  def __init__(self, t=1000.0):
    self.t = t

  def __call__(self):
    return self.t

  def sleep(self, s):
    self.t += s


def _policy(clock, **over):
  defaults = dict(min_backends=1, max_backends=4, burn_high=2.0,
                  burn_recover=1.0, queue_high=8.0, queue_recover=2.0,
                  util_low=0.15, util_recover=0.35, up_sustain_s=2.0,
                  down_sustain_s=20.0, up_cooldown_s=10.0,
                  down_cooldown_s=30.0, budget=4, budget_window_s=300.0)
  defaults.update(over)
  return AutoscalePolicy(AutoscaleConfig(**defaults), clock=clock)


CALM = {"fast_burn": 0.0, "queue_depth": 0.0, "brownout_level": 0,
        "util": None}


def _step(policy, clock, signals, n, dt=1.0):
  clock.t += dt
  return policy.decide(signals, n)


# --- config validation ---------------------------------------------------


def test_config_rejects_empty_or_inverted_bands():
  with pytest.raises(ValueError):
    AutoscaleConfig(queue_high=2.0, queue_recover=2.0)
  with pytest.raises(ValueError):
    AutoscaleConfig(burn_high=1.0, burn_recover=2.0)
  with pytest.raises(ValueError):
    AutoscaleConfig(util_low=0.5, util_recover=0.4)
  with pytest.raises(ValueError):
    AutoscaleConfig(min_backends=3, max_backends=2)
  with pytest.raises(ValueError):
    AutoscaleConfig(up_sustain_s=0.0)
  with pytest.raises(ValueError):
    AutoscaleConfig(budget=0)


# --- scale-up: trip, sustain, hysteresis ---------------------------------


def test_policy_scale_up_needs_sustained_pressure():
  clock = FakeClock()
  policy = _policy(clock, up_sustain_s=2.0)
  hot = dict(CALM, queue_depth=9.0)
  policy.decide(hot, 1)  # first sample: dt=0, nothing accumulated
  assert _step(policy, clock, hot, 1, dt=1.0) is None  # 1.0s < 2.0s
  action = _step(policy, clock, hot, 1, dt=1.0)
  assert action is not None and action["action"] == "up"
  assert "queue depth" in action["reason"]
  assert policy.ups == 1


def test_policy_each_signal_trips_scale_up():
  for signals, token in (
      (dict(CALM, fast_burn=2.5), "fast-burn"),
      (dict(CALM, queue_depth=8.0), "queue depth"),
      (dict(CALM, brownout_level=1), "brownout"),
  ):
    clock = FakeClock()
    policy = _policy(clock, up_sustain_s=1.0)
    policy.decide(signals, 1)
    action = _step(policy, clock, signals, 1, dt=1.0)
    assert action is not None and action["action"] == "up"
    assert token in action["reason"]


def test_policy_hysteresis_band_freezes_pressure():
  clock = FakeClock()
  policy = _policy(clock, queue_high=8.0, queue_recover=2.0,
                   up_sustain_s=3.0)
  hot = dict(CALM, queue_depth=9.0)
  mid = dict(CALM, queue_depth=5.0)  # between recover and high
  policy.decide(hot, 1)
  _step(policy, clock, hot, 1, dt=2.0)  # 2.0s accumulated
  # Hovering mid-band: pressure neither grows nor resets...
  for _ in range(10):
    assert _step(policy, clock, mid, 1, dt=1.0) is None
  assert policy.snapshot()["pressure_s"] == 2.0
  # ...so re-tripping needs only the remaining 1.0s, not a fresh 3.0s.
  action = _step(policy, clock, hot, 1, dt=1.0)
  assert action is not None and action["action"] == "up"


def test_policy_calm_resets_pressure():
  clock = FakeClock()
  policy = _policy(clock, up_sustain_s=3.0)
  hot = dict(CALM, queue_depth=9.0)
  policy.decide(hot, 1)
  _step(policy, clock, hot, 1, dt=2.0)
  _step(policy, clock, CALM, 1, dt=1.0)  # below every recover: reset
  assert policy.snapshot()["pressure_s"] == 0.0
  policy.decide(hot, 1)
  assert _step(policy, clock, hot, 1, dt=2.0) is None  # re-earning


# --- scale-down: idle accumulation ---------------------------------------


def test_policy_scale_down_on_sustained_idleness():
  clock = FakeClock()
  policy = _policy(clock, down_sustain_s=5.0)
  idle = dict(CALM, util=0.05)
  policy.decide(idle, 3)
  for _ in range(4):
    assert _step(policy, clock, idle, 3, dt=1.0) is None
  action = _step(policy, clock, idle, 3, dt=1.0)
  assert action is not None and action["action"] == "down"
  assert "utilization" in action["reason"]
  assert policy.downs == 1


def test_policy_unmeasurable_util_freezes_idle_time():
  clock = FakeClock()
  policy = _policy(clock, down_sustain_s=4.0)
  idle = dict(CALM, util=0.05)
  policy.decide(idle, 3)
  _step(policy, clock, idle, 3, dt=3.0)
  # A None-util sample (membership change, first sample): freeze.
  _step(policy, clock, dict(CALM, util=None), 3, dt=10.0)
  assert policy.snapshot()["idle_s"] == 3.0
  # Mid-band utilization also freezes (neither idle nor busy).
  _step(policy, clock, dict(CALM, util=0.25), 3, dt=10.0)
  assert policy.snapshot()["idle_s"] == 3.0
  action = _step(policy, clock, idle, 3, dt=1.0)
  assert action is not None and action["action"] == "down"


def test_policy_busy_or_tripping_resets_idle_time():
  clock = FakeClock()
  policy = _policy(clock, down_sustain_s=4.0)
  idle = dict(CALM, util=0.05)
  policy.decide(idle, 3)
  _step(policy, clock, idle, 3, dt=3.0)
  _step(policy, clock, dict(CALM, util=0.9), 3, dt=1.0)  # busy: reset
  assert policy.snapshot()["idle_s"] == 0.0
  policy.decide(idle, 3)
  _step(policy, clock, idle, 3, dt=3.0)
  # A scale-up trip also resets idle (the signals contradict).
  _step(policy, clock, dict(CALM, queue_depth=9.0, util=0.05), 3, dt=1.0)
  assert policy.snapshot()["idle_s"] == 0.0


# --- gates: clamps, cooldowns, budget ------------------------------------


def test_policy_clamps_at_pool_bounds_but_keeps_accumulation():
  clock = FakeClock()
  policy = _policy(clock, up_sustain_s=1.0, max_backends=2,
                   down_sustain_s=2.0, min_backends=1,
                   up_cooldown_s=0.0, down_cooldown_s=0.0)
  hot = dict(CALM, queue_depth=9.0)
  policy.decide(hot, 2)
  assert _step(policy, clock, hot, 2, dt=2.0) is None  # at max: held
  assert policy.clamped_max == 1
  # The moment headroom appears, the held pressure fires immediately.
  action = _step(policy, clock, hot, 1, dt=0.001)
  assert action is not None and action["action"] == "up"
  idle = dict(CALM, util=0.0)
  policy.decide(idle, 1)
  assert _step(policy, clock, idle, 1, dt=3.0) is None  # at min: held
  assert policy.clamped_min == 1
  action = _step(policy, clock, idle, 2, dt=0.001)
  assert action is not None and action["action"] == "down"


def test_policy_cooldown_holds_then_releases():
  clock = FakeClock()
  policy = _policy(clock, up_sustain_s=1.0, up_cooldown_s=10.0)
  hot = dict(CALM, queue_depth=9.0)
  policy.decide(hot, 1)
  assert _step(policy, clock, hot, 1, dt=1.0)["action"] == "up"
  # Still hot: the next sustained trip is held by the cooldown...
  assert _step(policy, clock, hot, 2, dt=2.0) is None
  assert policy.cooldown_holds == 1
  # ...and fires on the first sample past it (accumulation was kept).
  assert _step(policy, clock, hot, 2, dt=8.1)["action"] == "up"


def test_policy_budget_exhaustion_denies_then_window_slides():
  clock = FakeClock()
  policy = _policy(clock, up_sustain_s=1.0, up_cooldown_s=0.0,
                   budget=1, budget_window_s=60.0)
  hot = dict(CALM, queue_depth=9.0)
  policy.decide(hot, 1)
  assert _step(policy, clock, hot, 1, dt=1.0)["action"] == "up"
  assert _step(policy, clock, hot, 2, dt=2.0) is None  # budget dry
  assert policy.denied_budget == 1
  clock.t += 60.1  # the window slides past the spend
  assert _step(policy, clock, hot, 2, dt=1.0)["action"] == "up"
  snap = policy.snapshot()
  assert snap["budget"]["refused"] == 1 and snap["ups"] == 2


# --- the actuator over fakes ---------------------------------------------


class FakeScalePool:
  """Elastic pool fake: spawn/retire/kill bookkeeping with an optional
  ``on_kill`` probe so tests can assert WHAT WAS TRUE at kill time."""

  def __init__(self, backends=("b0", "b1")):
    self.addrs = {b: f"host-{b}:1" for b in backends}
    self._alive = {b: True for b in backends}
    self.spawned: list[str] = []
    self.retired: list[str] = []
    self.kills: list[tuple[str, int]] = []
    self.fail_spawn = False
    self.on_kill = None

  def addresses(self):
    return dict(self.addrs)

  def alive(self, backend_id):
    return self._alive.get(backend_id, False)

  def kill(self, backend_id, sig=signal.SIGKILL):
    if self.on_kill is not None:
      self.on_kill(backend_id, sig)
    self.kills.append((backend_id, sig))
    self._alive[backend_id] = False

  def spawn_backend(self, backend_id=None):
    if self.fail_spawn:
      raise RuntimeError("no capacity")
    bid = backend_id or f"b{len(self.addrs)}"
    self.addrs[bid] = f"host-{bid}:1"
    self._alive[bid] = True
    self.spawned.append(bid)
    return bid, self.addrs[bid]

  def add_address(self, backend_id, address):
    self.addrs[backend_id] = address
    self._alive[backend_id] = True

  def retire(self, backend_id):
    self.retired.append(backend_id)
    self.addrs.pop(backend_id, None)
    self._alive.pop(backend_id, None)

  def restart(self, backend_id):
    self._alive[backend_id] = True
    return self.addrs[backend_id]


class FakeTransport:
  """Method-aware ``address -> handler(method, path)`` transport; a
  missing handler is a dead host (ConnectionError)."""

  def __init__(self):
    self.handlers = {}
    self.log: list[tuple[str, str, str]] = []  # (address, method, path)

  def set_backend(self, address, state=None):
    state = state if state is not None else {}
    state.setdefault("status", "ok")
    state.setdefault("queue_depth", 0)
    state.setdefault("busy_s", 0.0)
    state.setdefault("render_ok", True)

    def handler(method, path):
      if path == "/healthz":
        return 200, {}, json.dumps({"status": state["status"]}).encode()
      if path == "/stats":
        return 200, {}, json.dumps({
            "queue_depth": state["queue_depth"],
            "device_render_seconds": state["busy_s"]}).encode()
      if path.startswith("/scene/") and path.endswith("/manifest"):
        if state.get("digest") is None:
          return 404, {}, b"{}"
        return 200, {}, json.dumps(
            {"scene_digest": state["digest"]}).encode()
      if path == "/render":
        return (200, {}, b"{}") if state["render_ok"] else (503, {}, b"{}")
      return 404, {}, b"{}"

    self.handlers[address] = handler
    return state

  def set_dead(self, address):
    self.handlers.pop(address, None)

  def request(self, method, url, body=None, headers=None, timeout=30.0):
    address, _, path = url[len("http://"):].partition("/")
    self.log.append((address, method, "/" + path))
    handler = self.handlers.get(address)
    if handler is None:
      raise ConnectionError(f"connection refused: {address}")
    return handler(method, "/" + path)


SCENES = ("scene_000", "scene_001")


def _elastic(backends=("b0", "b1"), gossip=None, config=None, **kw):
  clock = FakeClock()
  pool = FakeScalePool(backends)
  transport = FakeTransport()
  for addr in pool.addrs.values():
    transport.set_backend(addr)
  router = Router(pool.addresses(), replication=2, transport=transport,
                  clock=clock)
  policy = AutoscalePolicy(
      config or AutoscaleConfig(up_sustain_s=1.0, down_sustain_s=2.0,
                                up_cooldown_s=0.0, down_cooldown_s=0.0,
                                queue_high=4.0, queue_recover=1.0),
      clock=clock)
  asc = Autoscaler(policy, pool, router, gossip=gossip,
                   events=router.events, scenes=SCENES,
                   transport=transport, clock=clock, sleep=clock.sleep,
                   eval_interval_s=0.5, drain_s=0.25, warm_timeout_s=5.0,
                   **kw)
  return clock, pool, transport, router, asc


def test_warm_backend_manifest_fast_path_and_render_fallback():
  clock = FakeClock()
  transport = FakeTransport()
  transport.set_backend("donor:1", {"digest": "abc"})
  transport.set_backend("new:1", {"digest": "abc", "render_ok": False})
  out = warm_backend("new:1", SCENES, donors=("donor:1",),
                     transport=transport, timeout_s=2.0, clock=clock,
                     sleep=clock.sleep)
  assert out["ok"] and set(out["modes"].values()) == {"manifest"}
  # No manifests anywhere: the identity-pose render IS the warmup.
  transport.set_backend("new2:1", {})
  out = warm_backend("new2:1", SCENES, donors=("donor2:1",),
                     transport=transport, timeout_s=2.0, clock=clock,
                     sleep=clock.sleep)
  assert out["ok"] and set(out["modes"].values()) == {"render"}
  # Unreachable backend: deadline expires, never raises.
  out = warm_backend("dead:1", SCENES, transport=transport,
                     timeout_s=1.0, clock=clock, sleep=clock.sleep)
  assert not out["ok"] and sorted(out["failed"]) == sorted(SCENES)


def test_scale_up_warms_before_the_ring_admits():
  clock, pool, transport, router, asc = _elastic()
  admitted_at_warm_time = []
  state = transport.set_backend("host-b2:1")
  orig = transport.handlers["host-b2:1"]

  def probe(method, path):
    if path == "/render":
      admitted_at_warm_time.append("b2" in router.backend_ids())
    return orig(method, path)

  transport.handlers["host-b2:1"] = probe
  out = asc.scale_up("test pressure")
  assert out["action"] == "up" and out["backend"] == "b2"
  assert pool.spawned == ["b2"]
  assert "b2" in router.backend_ids()
  # THE ordering pin: every warming probe ran BEFORE the ring admit.
  assert admitted_at_warm_time and not any(admitted_at_warm_time)
  assert out["warm"]["ok"] and out["warm"]["modes"]
  assert router.events.count("autoscale_up") == 1
  assert router.metrics.snapshot()["autoscale"]["ups"] == 1


def test_scale_up_unwarmable_spawn_is_retired_not_admitted():
  clock, pool, transport, router, asc = _elastic()
  # No handler for the spawn's address: it never answers a warm probe.
  out = asc.scale_up("test pressure")
  assert out["action"] == "abort" and out["of"] == "up"
  assert "b2" not in router.backend_ids()
  assert pool.retired == ["b2"]  # no stranded process
  assert "b2" not in pool.addresses()
  assert router.events.count("autoscale_abort") == 1
  assert router.metrics.snapshot()["autoscale"]["aborts"] == 1
  assert asc.snapshot()["aborts"] == 1


def test_scale_up_failed_spawn_aborts():
  clock, pool, transport, router, asc = _elastic()
  pool.fail_spawn = True
  out = asc.scale_up("test pressure")
  assert out["action"] == "abort"
  assert router.backend_ids() == ["b0", "b1"]
  assert router.events.count("autoscale_abort") == 1


def test_provision_hook_spawns_remote_capacity():
  calls = []

  class Done:
    returncode = 0
    stdout = "joining fleet...\n127.9.9.9:7777\n"
    stderr = ""

  def runner(argv, **kw):
    calls.append((argv, kw))
    return Done()

  clock, pool, transport, router, asc = _elastic(
      provision_hook=["./provision.sh", "--zone", "z1"], runner=runner)
  transport.set_backend("127.9.9.9:7777")
  out = asc.scale_up("join pressure")
  assert out["action"] == "up" and out["address"] == "127.9.9.9:7777"
  assert calls[0][0] == ["./provision.sh", "--zone", "z1", "b2"]
  assert calls[0][1]["timeout"] == asc.hook_timeout_s
  assert pool.addresses()["b2"] == "127.9.9.9:7777"
  assert "b2" in router.backend_ids()
  assert pool.spawned == []  # the hook provisioned, not the local pool


def test_provision_hook_without_address_aborts():
  class Bad:
    returncode = 0
    stdout = "no address here\n"
    stderr = ""

  clock, pool, transport, router, asc = _elastic(
      provision_hook=["./provision.sh"], runner=lambda *a, **k: Bad())
  out = asc.scale_up("join pressure")
  assert out["action"] == "abort"
  assert "host:port" in out["reason"]


def test_next_id_skips_pool_and_router_and_reuses_retired():
  clock, pool, transport, router, asc = _elastic(("b0", "b2"))
  # b1 free (pool has b0+b2, router has b0+b2): lowest gap wins.
  assert asc._next_id() == "b1"


def test_scale_down_ejects_before_sigterm_and_moves_ring_last():
  clock, pool, transport, router, asc = _elastic(("b0", "b1", "b2"))
  seen = []
  pool.on_kill = lambda b, sig: seen.append(
      (sig, b in router.ejected(), b in router.backend_ids()))
  out = asc.scale_down("idle fleet")
  # Victim: the highest-numbered backend.
  assert out["action"] == "down" and out["backend"] == "b2"
  # At SIGTERM time the victim was already ejected (drained) but still
  # in the ring — the ring moves only after the process is retired.
  assert seen == [(signal.SIGTERM, True, True)]
  assert router.backend_ids() == ["b0", "b1"]
  assert pool.retired == ["b2"]
  assert router.events.count("autoscale_down") == 1
  assert router.metrics.snapshot()["autoscale"]["downs"] == 1


def test_scale_down_skips_quarantined_victims():
  clock, pool, transport, router, asc = _elastic(("b0", "b1", "b2"))

  class Sup:
    forgotten = []

    def quarantined(self):
      return ["b2"]

    def forget(self, b):
      self.forgotten.append(b)

  asc.supervisor = Sup()
  out = asc.scale_down("idle fleet")
  # b2 is evidence, not capacity: the next-highest backend retires.
  assert out["backend"] == "b1"
  assert asc.supervisor.forgotten == ["b1"]
  assert set(router.backend_ids()) == {"b0", "b2"}


def test_scale_down_records_retired_verdict_in_gossip():
  gossip = GossipState("routerA", clock=FakeClock(5000.0))
  clock, pool, transport, router, asc = _elastic(("b0", "b1"),
                                                 gossip=gossip)
  asc.scale_down("idle fleet")
  obs = gossip.observation("b1")
  assert obs["fields"]["state"] == "retired"
  assert not obs["fields"]["quarantined"]
  rec = gossip.observation(AUTOSCALE_KEY)["fields"]
  assert rec["action"] == "down" and rec["phase"] == "done"


def test_tick_closes_the_loop_from_signals_to_actions():
  gossip = GossipState("routerA", clock=FakeClock(5000.0))
  clock, pool, transport, router, asc = _elastic(gossip=gossip)
  # Saturate both backends' reported queues: tick must trip, sustain,
  # spawn b2, warm it, and admit it.
  for addr in list(pool.addrs.values()):
    transport.set_backend(addr, {"queue_depth": 9})
  transport.set_backend("host-b2:1")
  assert asc.tick() is None  # first sample: accumulating
  clock.t += 1.1
  out = asc.tick()
  assert out is not None and out["action"] == "up"
  assert "b2" in router.backend_ids()
  assert gossip.observation(AUTOSCALE_KEY)["fields"]["phase"] == "done"
  # Calm + idle: utilization deltas go to zero and the pool shrinks.
  for addr in list(pool.addrs.values()):
    transport.set_backend(addr, {"queue_depth": 0, "busy_s": 4.0})
  downs = 0
  for _ in range(20):
    clock.t += 1.0
    out = asc.tick()
    if out is not None and out.get("action") == "down":
      downs += 1
  assert downs >= 1
  assert len(router.backend_ids()) < 3


def test_eval_interval_rate_limits_signal_fanout():
  clock, pool, transport, router, asc = _elastic()
  asc.tick()
  n = len(transport.log)
  clock.t += 0.1  # below eval_interval_s=0.5
  asc.tick()
  assert len(transport.log) == n  # no second /stats fan-out
  clock.t += 0.5
  asc.tick()
  assert len(transport.log) > n


# --- leaseholder death mid-scale-out -------------------------------------


class TakeoverLease:
  """First try_acquire is a takeover of a dead leader."""

  owner = "routerB"

  def try_acquire(self):
    return {"takeover": True, "previous": "routerA"}

  def heartbeat(self):
    return None

  def release(self):
    return None


def _takeover_fleet(dead_leader_records, spawn_alive: bool,
                    spawn_exists: bool = True):
  """Fleet B adopting gossip that holds a half-finished scale-out: the
  dead leader spawned b2 (phase 'warming') and quarantined b1 before
  dying. ``spawn_alive`` decides whether b2 answers its /healthz;
  ``spawn_exists`` whether its process is in the pool at all."""
  wall = FakeClock(5000.0)
  stateA = GossipState("routerA", clock=wall)
  for key, fields in dead_leader_records:
    stateA.observe(key, **fields)
  stateB = GossipState("routerB", clock=wall)
  stateB.merge(stateA.wire())

  clock = FakeClock()
  pool = FakeScalePool(("b0", "b1"))
  pool._alive["b1"] = False  # the quarantined crash-looper is down
  if spawn_exists:
    pool.add_address("b2", "host-b2:1")  # the stranded spawn's process
  transport = FakeTransport()
  transport.set_backend("host-b0:1")
  if spawn_alive:
    transport.set_backend("host-b2:1")
  router = Router({"b0": "host-b0:1", "b1": "host-b1:1"}, replication=2,
                  transport=transport, clock=clock)
  policy = AutoscalePolicy(AutoscaleConfig(), clock=clock)
  asc = Autoscaler(policy, pool, router, gossip=stateB,
                   events=router.events, scenes=SCENES,
                   transport=transport, clock=clock, sleep=clock.sleep,
                   warm_timeout_s=2.0)
  sup = FleetSupervisor(pool, router=router, events=router.events,
                        transport=transport, clock=clock,
                        sleep=lambda s: None, load_refresh_s=0,
                        lease=TakeoverLease(), gossip=stateB,
                        autoscaler=asc)
  return stateB, pool, transport, router, asc, sup


_LEADER_RECORDS = (
    ("b1", dict(state="quarantined", quarantined=True, ejected=True,
                reason="crash loop", budget_ages_s=[1.0, 3.0])),
    (AUTOSCALE_KEY, dict(seq=7, action="up", backend="b2",
                         address="host-b2:1", phase="warming",
                         reason="queue depth 9.0 >= 4")),
)


def test_takeover_completes_a_half_finished_scale_out():
  stateB, pool, transport, router, asc, sup = _takeover_fleet(
      _LEADER_RECORDS, spawn_alive=True)
  sup.tick()  # acquire-as-takeover: adopt observations, then converge
  # The stranded spawn was warmed and admitted by the NEW leader.
  assert "b2" in router.backend_ids()
  assert asc.converges == 1
  assert stateB.observation(AUTOSCALE_KEY)["fields"]["phase"] == "done"
  assert stateB.observation(AUTOSCALE_KEY)["fields"]["seq"] == 7
  assert asc._seq >= 7  # future decisions version past the adopted one
  # The dead leader's quarantine verdict survived adoption intact.
  assert sup.state("b1") == FleetSupervisor.QUARANTINED
  assert "b1" in router.ejected()
  assert sup.snapshot()["backends"]["b1"]["budget"]["in_window"] == 2
  assert router.events.count("supervision_takeover") == 1
  assert router.events.count("autoscale_up") == 1


def test_takeover_retires_a_stranded_unreachable_spawn():
  stateB, pool, transport, router, asc, sup = _takeover_fleet(
      _LEADER_RECORDS, spawn_alive=False)
  sup.tick()
  # The spawn never answered: retired, not leaked, not admitted.
  assert "b2" not in router.backend_ids()
  assert "b2" in pool.retired
  assert "b2" not in pool.addresses()
  assert stateB.observation(AUTOSCALE_KEY)["fields"]["phase"] == "aborted"
  assert router.events.count("autoscale_abort") == 1
  assert sup.state("b1") == FleetSupervisor.QUARANTINED


def test_takeover_with_finished_record_is_a_noop():
  records = (("b2", dict(state="retired", quarantined=False, ejected=True,
                         reason="autoscale retire", budget_ages_s=[])),
             (AUTOSCALE_KEY, dict(seq=9, action="down", backend="b2",
                                  address=None, phase="done",
                                  reason="idle")),)
  stateB, pool, transport, router, asc, sup = _takeover_fleet(
      records, spawn_alive=False, spawn_exists=False)
  sup.tick()
  # A done record converges to nothing; the retired backend is NOT
  # resurrected as a supervision entry (the skip guard).
  assert asc.converges == 0 and asc.aborts == 0
  assert "b2" not in sup.snapshot()["backends"]
  assert asc._seq >= 9


def test_supervisor_forget_refuses_quarantined_records():
  stateB, pool, transport, router, asc, sup = _takeover_fleet(
      _LEADER_RECORDS, spawn_alive=True)
  sup.tick()
  with pytest.raises(ValueError):
    sup.forget("b1")
  assert sup.state("b1") == FleetSupervisor.QUARANTINED


# --- the live acceptance arc ---------------------------------------------


@pytest.fixture(scope="module")
def elastic_fleet(healed_backends):
  pool, backends = healed_backends
  router = Router(backends, replication=2, breaker_threshold=2,
                  breaker_reset_s=0.5, render_timeout_s=120.0)
  yield pool, router


def _render_body(sid, tx=0.0):
  pose = np.eye(4)
  pose[0, 3] = tx
  return json.dumps({"scene_id": sid, "pose": pose.tolist()}).encode()


def test_fleet_scale_up_warmed_admit_then_drainless_retire(elastic_fleet):
  """THE acceptance arc (ISSUE 19): under live closed-loop traffic the
  fleet grows by one REAL backend (spawned, warmed over HTTP, only
  then admitted to the ring) and shrinks back via the drainless
  eject -> drain -> SIGTERM -> retire choreography — with ZERO failed
  client requests across both transitions."""
  pool, router = elastic_fleet
  sids = pool.scene_ids()
  before = sorted(router.backend_ids())
  policy = AutoscalePolicy(AutoscaleConfig(
      min_backends=len(before), max_backends=len(before) + 1,
      up_cooldown_s=0.0, down_cooldown_s=0.0))
  asc = Autoscaler(policy, pool, router, events=router.events,
                   scenes=sids, drain_s=0.3, warm_timeout_s=120.0,
                   log=lambda m: print(m, file=sys.stderr))

  stop = threading.Event()
  failures: list[str] = []
  ok = [0] * 3
  lock = threading.Lock()

  def worker(w):
    i = 0
    while not stop.is_set():
      sid = sids[(w + i) % len(sids)]
      i += 1
      try:
        status, _, _ = router.forward_render(
            sid, _render_body(sid, tx=0.002 * (i % 5)))
      except Exception as e:  # noqa: BLE001 - any escape is a failure
        with lock:
          failures.append(f"{sid}: {e!r}")
        continue
      if status == 200:
        ok[w] += 1
      else:
        with lock:
          failures.append(f"{sid}: http {status}")

  threads = [threading.Thread(target=worker, args=(w,), daemon=True)
             for w in range(3)]
  for t in threads:
    t.start()
  new_backend = None
  try:
    deadline = time.monotonic() + 60.0
    while sum(ok) < 5 and time.monotonic() < deadline:
      time.sleep(0.05)  # traffic established before the ramp

    up = asc.scale_up("acceptance ramp")
    assert up["action"] == "up", up
    new_backend = up["backend"]
    assert new_backend not in before
    assert new_backend in router.backend_ids()
    assert pool.alive(new_backend)
    # Warmed means WARMED: every ring key the new backend now owns was
    # probed (manifest-diff or a real render) before the ring moved.
    owned = [k for k, placement in
             router.resize_preview(keys=sids)["after"].items()
             if new_backend in placement]
    assert up["warm"]["ok"]
    assert set(up["warm"]["modes"]) == set(owned)
    assert router.events.count("autoscale_up") >= 1

    end = time.monotonic() + 0.5
    while time.monotonic() < end:
      time.sleep(0.05)  # let traffic ride the grown fleet

    down = asc.scale_down("acceptance ramp-down")
    assert down["action"] == "down", down
    # Highest-numbered victim: the backend we just added.
    assert down["backend"] == new_backend
    new_backend = None
    assert sorted(router.backend_ids()) == before
    assert router.events.count("autoscale_down") >= 1

    end = time.monotonic() + 0.5
    while time.monotonic() < end:
      time.sleep(0.05)  # the shrunk fleet must still serve cleanly
  finally:
    stop.set()
    for t in threads:
      t.join(30)
    if new_backend is not None:  # a failed assert must not leak a proc
      pool.retire(new_backend)

  assert failures == [], failures[:10]  # ZERO failed client requests
  assert sum(ok) > 0
  assert router.ejected() == []
  snap = router.metrics.snapshot()["autoscale"]
  assert snap["ups"] >= 1 and snap["downs"] >= 1
  # Every scene still serves from the restored pool.
  for sid in sids:
    status, _, _ = router.forward_render(sid, _render_body(sid))
    assert status == 200
