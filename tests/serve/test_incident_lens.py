"""Incident lens + resource attribution: the acceptance pins.

Two layers under test. ``obs/attrib.py``: the bounded
``(scene x class x level)`` resource ledger whose cell sums must
reconcile exactly with the metrics layer's pre-existing ``requests`` /
``phase_seconds`` totals — in-process AND through the cluster router's
pool merge (every ``mpi_serve_attrib_*`` family additive, never in a
NON_ADDITIVE drop list). ``obs/incident.py``: the SLO-triggered black
box — one bundle per fire edge (dedup until clear), bounded keep-K disk
ring, resume across processes, and the shipper hand-off that survives a
sink outage with zero loss.

The acceptance drill at the bottom is the end-to-end arc: a one-scene
latency fault under real load fires ``latency_p99:scene_*``, the
recorder auto-captures a bundle whose exemplar trace id resolves at
``/debug/traces``, whose tsdb window spans the spike, and whose
attribution cells name the hot scene.
"""

import json
import os
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from mpi_vision_tpu.obs import attrib as attrib_mod
from mpi_vision_tpu.obs import hist as hist_mod
from mpi_vision_tpu.obs import incident as incident_mod
from mpi_vision_tpu.obs import prom
from mpi_vision_tpu.obs import ship as ship_mod
from mpi_vision_tpu.obs import slo as slo_mod
from mpi_vision_tpu.obs import tsdb as tsdb_mod
from mpi_vision_tpu.obs.slo import SloConfig, SloTracker
from mpi_vision_tpu.obs.trace import Tracer
from mpi_vision_tpu.serve import (
    Fault,
    FaultyEngine,
    RenderEngine,
    RenderService,
    make_http_server,
)
from mpi_vision_tpu.serve import brownout as brownout_mod
from mpi_vision_tpu.serve.cluster.router import Router

H = W = 16
P = 4


class FakeClock:
  def __init__(self, t=1000.0):
    self.t = t

  def __call__(self):
    return self.t

  def advance(self, dt):
    self.t += dt
    return self.t


def _pose(tx=0.0):
  pose = np.eye(4, dtype=np.float32)
  pose[0, 3] = tx
  return pose


def _get(port, path):
  with urllib.request.urlopen(
      f"http://127.0.0.1:{port}{path}", timeout=30) as resp:
    return resp.status, resp.read()


def _get_json(port, path):
  status, body = _get(port, path)
  return status, json.loads(body)


# --- the attribution ledger ------------------------------------------------


class TestAttribLedger:

  def test_cells_accumulate_and_rank_hottest_first(self):
    led = attrib_mod.AttribLedger()
    for _ in range(3):
      led.record("a", "interactive", 0,
                 device={"h2d": 0.001, "compute": 0.01, "readback": 0.001},
                 queue_wait_s=0.002)
    led.record("b", "background", 2,
               device={"h2d": 0.01, "compute": 0.2, "readback": 0.01})
    led.record_bytes("a", "interactive", 0, nbytes=4096)
    led.record_tiles("a", "interactive", 0, tiles=7)
    led.record("a", "interactive", 0, edge="hit")
    led.record("a", "interactive", 0, edge="warp")
    snap = led.snapshot()
    assert snap["cells_total"] == 2 and snap["scenes"] == 2
    hot, cold = snap["cells"]
    # "b" burned more device time despite fewer requests — ranking is by
    # device-seconds, not request count.
    assert (hot["scene"], hot["class"], hot["level"]) == ("b", "background", 2)
    assert (cold["scene"], cold["class"]) == ("a", "interactive")
    assert cold["requests"] == 5
    assert cold["bytes_out"] == 4096 and cold["tiles_touched"] == 7
    assert cold["edge_hits"] == 1 and cold["edge_warps"] == 1
    assert cold["queue_wait_s"] == pytest.approx(0.006)
    totals = snap["totals"]
    assert totals["requests"] == 6
    assert totals["device_s"]["compute"] == pytest.approx(0.23)
    # top= truncates the list, not the population count.
    top = led.snapshot(top=1)
    assert len(top["cells"]) == 1 and top["cells_total"] == 2
    assert led.top_cells(1)[0]["scene"] == "b"
    led.reset()
    assert led.snapshot()["cells_total"] == 0

  def test_scene_cap_folds_overflow_and_unlabeled_class(self):
    led = attrib_mod.AttribLedger(attrib_mod.AttribConfig(scene_cap=1))
    led.record("a", "interactive", 0)
    led.record("b", None, 0)  # past the cap AND unlabeled
    led.record("c", "prefetch", 1)
    snap = led.snapshot()
    assert snap["scenes"] == 1 and snap["overflow_requests"] == 2
    scenes = {c["scene"] for c in snap["cells"]}
    assert scenes == {"a", attrib_mod.OVERFLOW_SCENE}
    other = [c for c in snap["cells"]
             if c["scene"] == attrib_mod.OVERFLOW_SCENE]
    assert {(c["class"], c["level"]) for c in other} == \
        {(attrib_mod.UNLABELED_CLASS, 0), ("prefetch", 1)}
    with pytest.raises(ValueError):
      attrib_mod.AttribConfig(scene_cap=0)

  def test_conservation_reconciles_and_catches_leaks(self):
    led = attrib_mod.AttribLedger()
    led.record("a", "interactive", 0,
               device={"h2d": 0.25, "compute": 1.5, "readback": 0.0625})
    led.record("b", "prefetch", 1,
               device={"h2d": 0.125, "compute": 0.5, "readback": 0.03125})
    ref = {"h2d": 0.375, "compute": 2.0, "readback": 0.09375}
    con = led.conservation(2, ref)
    assert con["ok"] is True and con["request_delta"] == 0
    # A dropped request or leaked device second must flip the verdict.
    assert led.conservation(3, ref)["ok"] is False
    bad = dict(ref, compute=2.5)
    assert led.conservation(2, bad)["ok"] is False
    # snapshot(reference=...) carries the same block.
    snap = led.snapshot(reference={"requests": 2,
                                   "device_phase_seconds": ref})
    assert snap["conservation"]["ok"] is True

  def test_merge_snapshots_aggregates_the_fleet(self):
    a, b = attrib_mod.AttribLedger(), attrib_mod.AttribLedger()
    a.record("s", "interactive", 0, device={"compute": 0.5})
    a.record("only_a", "background", 0)
    b.record("s", "interactive", 0, device={"compute": 0.25})
    merged = attrib_mod.merge_snapshots(
        [a.snapshot(), b.snapshot(), None, {}])
    assert merged["backends"] == 2
    shared = next(c for c in merged["cells"] if c["scene"] == "s")
    assert shared["requests"] == 2
    assert shared["device_s"]["compute"] == pytest.approx(0.75)
    assert merged["totals"]["requests"] == 3
    assert {c["scene"] for c in merged["cells"]} == {"s", "only_a"}

  def test_families_additive_and_conserved_through_pool_merge(self):
    """The router-merge conservation pin: two backends' expositions,
    summed exactly the way ``Router._render_metrics_text`` sums them
    (same drop set), must carry the fleet ledger — and no
    ``mpi_serve_attrib_*`` family may ever sit in a NON_ADDITIVE drop
    list, or the merge silently loses the ledger."""
    drop = (slo_mod.NON_ADDITIVE_FAMILIES | hist_mod.NON_ADDITIVE_FAMILIES
            | brownout_mod.NON_ADDITIVE_FAMILIES)
    assert not {f for f in drop if f.startswith(attrib_mod.PREFIX)}
    a, b = attrib_mod.AttribLedger(), attrib_mod.AttribLedger()
    a.record("s", "interactive", 0,
             device={"h2d": 0.125, "compute": 0.5, "readback": 0.0625},
             queue_wait_s=0.25)
    b.record("s", "interactive", 0,
             device={"h2d": 0.0625, "compute": 0.25, "readback": 0.03125})
    b.record_bytes("s", "interactive", 0, nbytes=1024)
    texts = [attrib_mod.registry(led.snapshot()).render() for led in (a, b)]
    families = prom.parse_metrics_text(
        prom.aggregate_metrics_texts(texts, drop=drop))

    def sample(family, want):
      for (_, labels), value in families[family]["samples"].items():
        if dict(labels) == want:
          return value
      raise AssertionError(f"no {family} sample labelled {want}")

    cell = {"scene": "s", "class": "interactive", "level": "0"}
    assert sample(attrib_mod.PREFIX + "requests_total", cell) == 2
    assert sample(attrib_mod.PREFIX + "device_seconds_total",
                  {**cell, "phase": "compute"}) == pytest.approx(0.75)
    assert sample(attrib_mod.PREFIX + "bytes_out_total", cell) == 1024
    # The summed exposition agrees with the structured fleet merge.
    merged = attrib_mod.merge_snapshots([a.snapshot(), b.snapshot()])
    assert merged["totals"]["device_s"]["compute"] == pytest.approx(0.75)

  def test_router_stats_merge_uses_backend_attrib_blocks(self):
    led = attrib_mod.AttribLedger()
    led.record("s", "interactive", 0, device={"compute": 0.5})
    per_backend = {"b0": {"attrib": led.snapshot()},
                   "b1": {"attrib": led.snapshot()},
                   "dead": {"error": "connection refused"}}
    fleet = Router._attrib_summary(per_backend)
    assert fleet["backends"] == 2
    assert fleet["totals"]["requests"] == 2
    assert fleet["totals"]["device_s"]["compute"] == pytest.approx(1.0)


# --- the incident recorder -------------------------------------------------


def _recorder(tmp_path, collect=None, on_bundle=None, **cfg_kw):
  cfg = incident_mod.IncidentConfig(dir=str(tmp_path / "inc"), **cfg_kw)
  clock = FakeClock()
  return incident_mod.IncidentRecorder(
      cfg, collect=collect, on_bundle=on_bundle,
      clock=clock, wall=FakeClock(2000.0))


class TestIncidentRecorder:

  def test_fire_edge_captures_bundle_on_disk(self, tmp_path):
    rec = _recorder(tmp_path,
                    collect=lambda alert: {"slo": {"seen": alert["alert"]}})
    rec.note_alert("latency_p99", True, {"fast_ms": 80.0})
    assert rec.stats()["pending"] == 1
    assert rec.drain() == 1
    stats = rec.stats()
    assert stats["captures"] == 1 and stats["pending"] == 0
    assert stats["firing"] == ["latency_p99"]
    (entry,) = rec.list()
    assert entry["id"] == "incident-000001"
    bundle = rec.get(entry["id"])
    assert bundle["kind"] == "mpi_incident"
    assert bundle["alert"]["alert"] == "latency_p99"
    assert bundle["alert"]["details"]["fast_ms"] == 80.0
    assert bundle["slo"] == {"seen": "latency_p99"}
    on_disk = os.path.join(str(tmp_path / "inc"), "incident-000001.json")
    assert os.path.exists(on_disk)
    assert not os.path.exists(on_disk + ".tmp")  # atomic publish

  def test_dedup_until_clear_then_one_bundle_per_fire_edge(self, tmp_path):
    rec = _recorder(tmp_path)
    rec.note_alert("latency", True)
    rec.note_alert("latency", True)  # still firing: suppressed
    assert rec.drain() == 1
    assert rec.stats()["suppressed"] == 1
    rec.note_alert("latency", False)  # clear releases the latch...
    assert rec.drain() == 0  # ...but never captures
    rec.note_alert("latency", True)
    assert rec.drain() == 1
    assert rec.stats()["captures"] == 2

  def test_keep_k_prunes_oldest(self, tmp_path):
    rec = _recorder(tmp_path, keep=2)
    for i in range(3):
      rec.note_alert(f"alert_{i}", True)
    assert rec.drain() == 3
    stats = rec.stats()
    assert stats["pruned"] == 1 and stats["bundles"] == 2
    ids = [e["id"] for e in rec.list()]
    assert ids == ["incident-000003", "incident-000002"]
    assert not os.path.exists(
        os.path.join(str(tmp_path / "inc"), "incident-000001.json"))
    with pytest.raises(KeyError):
      rec.get("incident-000001")

  def test_resume_continues_sequence_past_resident_bundles(self, tmp_path):
    first = _recorder(tmp_path)
    first.note_alert("latency", True)
    first.drain()
    second = _recorder(tmp_path)
    assert [e["id"] for e in second.list()] == ["incident-000001"]
    assert second.list()[0]["alert"] == "latency"
    assert second.get("incident-000001")["seq"] == 1
    second.note_alert("availability", True)
    second.drain()
    # The sequence resumed: the new bundle did NOT overwrite the old.
    assert [e["id"] for e in second.list()] == \
        ["incident-000002", "incident-000001"]

  def test_get_rejects_traversal_and_unknown_ids(self, tmp_path):
    rec = _recorder(tmp_path)
    for bad in ("../../etc/passwd", "incident-1x", "", "incident-000009"):
      with pytest.raises(KeyError):
        rec.get(bad)

  def test_failing_collector_still_leaves_a_bundle(self, tmp_path):
    def collect(alert):
      raise RuntimeError("stats deadlock")
    rec = _recorder(tmp_path, collect=collect)
    rec.note_alert("latency", True)
    assert rec.drain() == 1
    stats = rec.stats()
    assert stats["captures"] == 1 and stats["capture_errors"] == 1
    bundle = rec.get("incident-000001")
    assert "stats deadlock" in bundle["collect_error"]
    assert bundle["alert"]["alert"] == "latency"

  def test_on_bundle_failure_counts_ship_errors(self, tmp_path):
    def on_bundle(bundle):
      raise ConnectionError("sink down")
    rec = _recorder(tmp_path, on_bundle=on_bundle)
    rec.note_alert("latency", True)
    rec.drain()
    stats = rec.stats()
    assert stats["ship_errors"] == 1
    assert stats["captures"] == 1  # the bundle is durable regardless

  def test_worker_thread_stop_flushes_pending_jobs(self, tmp_path):
    rec = _recorder(tmp_path).start()
    rec.note_alert("latency", True)
    rec.stop()  # sentinel lands BEHIND the job: capture still happens
    assert rec.stats()["captures"] == 1

  def test_config_validation(self, tmp_path):
    with pytest.raises(ValueError):
      incident_mod.IncidentConfig(dir="")
    with pytest.raises(ValueError):
      incident_mod.IncidentConfig(dir=str(tmp_path), keep=0)
    with pytest.raises(ValueError):
      incident_mod.IncidentConfig(dir=str(tmp_path), tsdb_window_s=0)

  def test_registry_families_always_exposed(self):
    text = incident_mod.registry(None).render()
    families = prom.parse_metrics_text(text)
    assert {incident_mod.PREFIX + name for name in (
        "captures_total", "capture_errors_total", "suppressed_total",
        "pruned_total", "ship_errors_total", "pending", "bundles",
        "bundle_bytes")} == set(families)


# --- shipper hand-off: a sink outage loses nothing -------------------------


class FlakySink:
  def __init__(self, down=True):
    self.down = down
    self.bodies: list[dict] = []

  def post(self, url, body, timeout):
    if self.down:
      raise ConnectionError("sink down")
    self.bodies.append(json.loads(body))
    return 200


class TestLifecycleIncidentTap:
  """Fleet-lifecycle events -> incident episodes (PR 19): the black
  box covers quarantines, crash loops, gossip peer deaths, and
  autoscale decisions, deduped per episode through the recorder's
  existing fire/clear latch."""

  def _tap(self, tmp_path):
    rec = _recorder(tmp_path)
    return rec, incident_mod.LifecycleIncidentTap(rec)

  def test_quarantine_fires_once_per_episode(self, tmp_path):
    rec, tap = self._tap(tmp_path)
    ev = {"kind": "backend_quarantined", "backend": "b1", "restarts": 3}
    tap.note_event(ev)
    tap.note_event(ev)  # same episode: latched, no second bundle
    assert rec.drain() == 1
    assert rec.stats()["suppressed"] == 1
    (entry,) = rec.list()
    assert rec.get(entry["id"])["alert"]["alert"] == "quarantine:b1"
    # The readmit closes the episode; a NEW quarantine captures again.
    tap.note_event({"kind": "backend_readmit", "backend": "b1"})
    tap.note_event(ev)
    assert rec.drain() == 1

  def test_crash_loop_fires_on_second_attempt_only(self, tmp_path):
    rec, tap = self._tap(tmp_path)
    tap.note_event({"kind": "backend_restart", "backend": "b0",
                    "ok": True, "attempt": 1})
    assert rec.drain() == 0  # one restart is routine
    tap.note_event({"kind": "backend_restart", "backend": "b0",
                    "ok": True, "attempt": 2})
    assert rec.drain() == 1  # the loop is the incident
    # The quarantine verdict subsumes the crash-loop episode: it
    # closes that latch and opens its own.
    tap.note_event({"kind": "backend_quarantined", "backend": "b0"})
    assert rec.drain() == 1
    assert rec.stats()["firing"] == ["quarantine:b0"]

  def test_gossip_peer_death_clears_on_recovery(self, tmp_path):
    rec, tap = self._tap(tmp_path)
    down = {"kind": "gossip_peer_failure", "peer": "routerB",
            "error": "timeout"}
    tap.note_event(down)
    tap.note_event(down)
    assert rec.drain() == 1  # one bundle per outage, not per round
    tap.note_event({"kind": "gossip_peer_recovered", "peer": "routerB"})
    tap.note_event(down)
    assert rec.drain() == 1  # a NEW outage is a new episode

  def test_autoscale_decisions_capture_point_in_time(self, tmp_path):
    rec, tap = self._tap(tmp_path)
    # Distinct decisions (the gossip seq) each capture; the
    # self-clearing latch means none of them can ever wedge open.
    tap.note_event({"kind": "autoscale_up", "seq": 4, "backend": "b1"})
    tap.note_event({"kind": "autoscale_down", "seq": 5, "backend": "b1"})
    tap.note_event({"kind": "autoscale_abort", "seq": 6, "backend": "b2"})
    assert rec.drain() == 3
    assert rec.stats()["firing"] == []  # nothing latched
    assert tap.taps == 3

  def test_sink_parses_event_lines_and_never_throws(self, tmp_path):
    rec, tap = self._tap(tmp_path)
    tap.sink(json.dumps({"kind": "backend_quarantined", "backend": "b2",
                         "seq": 9, "ts_unix_s": 1.0}))
    tap.sink("not json {")          # counted, swallowed
    tap.sink(json.dumps({"kind": "scene_swap"}))  # unmapped: ignored
    assert rec.drain() == 1
    assert tap.errors == 1 and tap.taps == 1


def test_bundles_survive_sink_outage_and_drain_in_order(tmp_path):
  clock = FakeClock()
  sink = FlakySink(down=True)
  shipper = ship_mod.TelemetryShipper(
      ship_mod.ShipConfig(url="http://sink.invalid/ingest",
                          spool_dir=str(tmp_path / "spool")),
      transport=sink, clock=clock, sleep=lambda s: None)
  rec = _recorder(tmp_path, on_bundle=shipper.note_incident)
  rec.note_alert("latency_p99", True)
  rec.drain()
  shipper.tick()  # sink down: the bundle batch spools to disk
  clock.advance(1)
  rec.note_alert("availability", True)
  rec.drain()
  shipper.tick()
  stats = shipper.stats()
  assert stats["batches_shipped"] == 0 and stats["spool_files"] == 2
  assert rec.stats()["ship_errors"] == 0  # hand-off itself never raised
  sink.down = False
  shipper.tick()  # recovery drains the spool oldest-first
  assert shipper.stats()["spool_files"] == 0
  shipped = [b["id"] for body in sink.bodies
             for item in body["items"] if item["kind"] == "incidents"
             for b in item["bundles"]]
  assert shipped == ["incident-000001", "incident-000002"]  # zero loss


# --- the acceptance drill --------------------------------------------------


@pytest.fixture
def drill_service(tmp_path):
  """A real service under a one-scene latency fault: FaultyEngine for
  the injected slowness, SLO tracker on a fake clock (deterministic
  window edges), tracer for exemplars, tsdb ring + attribution ledger +
  an un-started incident recorder (drained manually)."""
  clock = FakeClock()
  tracker = SloTracker(
      SloConfig(fast_window_s=10.0, slow_window_s=60.0, bucket_s=1.0,
                min_requests=5, latency_threshold_s=0.05,
                quantile=0.99, per_scene=True),
      clock=clock)
  engine = FaultyEngine(RenderEngine(use_mesh=False))
  recorder = incident_mod.IncidentRecorder(
      incident_mod.IncidentConfig(dir=str(tmp_path / "inc")),
      clock=FakeClock(), wall=FakeClock(2000.0))
  holder = {}
  ring = tsdb_mod.TsdbRecorder(
      lambda: holder["svc"]._render_metrics_text(), clock=clock)
  svc = RenderService(engine=engine, resilience=None, max_batch=2,
                      max_wait_ms=1.0, slo=tracker, tracer=Tracer(),
                      tsdb=ring, attrib=attrib_mod.AttribConfig(),
                      incidents=recorder, metrics_ttl_s=0.0)
  holder["svc"] = svc
  svc.add_synthetic_scenes(2, height=H, width=W, planes=P)
  svc.warmup()
  svc.metrics.reset()
  yield svc, engine, tracker, recorder, ring, clock
  svc.close()


def test_acceptance_drill_latency_fault_to_black_box(drill_service):
  svc, engine, tracker, recorder, ring, clock = drill_service
  # Healthy traffic on scene_000, then a latency fault pinned to
  # scene_001: every one of its dispatches sleeps past the 50 ms
  # objective while scene_000 stays fast.
  for i in range(8):
    svc.render_traced("scene_000", _pose(0.001 * i), timeout=60)
  for i in range(6):
    engine.inject(Fault(kind="slow", seconds=0.08))
    svc.render_traced("scene_001", _pose(0.001 * i), timeout=60)
  ring.sample()  # the spike lands in the tsdb window
  firing = tracker.alerts_firing()
  assert "latency_p99:scene_001" in firing
  assert "latency_p99:scene_000" not in firing

  # Every fire edge captured exactly one bundle — no duplicates while
  # the alerts stay firing.
  recorder.drain()
  tracker.alerts_firing()  # re-evaluation: no new edges, no new bundles
  assert recorder.drain() == 0
  index = recorder.list()
  captured = [e["alert"] for e in index]
  assert sorted(captured) == sorted(set(captured))  # one per alert
  assert "latency_p99:scene_001" in captured

  entry = next(e for e in index if e["alert"] == "latency_p99:scene_001")
  bundle = recorder.get(entry["id"])
  # The bundle is the whole stitch: burn numbers, traces, the tsdb
  # window spanning the spike, events, and the attribution cells naming
  # the hot scene.
  details = bundle["alert"]["details"]
  assert details["scene"] == "scene_001"
  assert details["fast_ms"] > 50.0
  window = bundle["tsdb_window"]
  assert window["window_s"] == recorder.config.tsdb_window_s
  assert "mpi_serve_requests_total" in window["families"]
  assert bundle["slo"]["alerts_firing"]
  assert {c["scene"] for c in bundle["attrib_top"]} >= {"scene_001"}
  assert bundle["traces"]["finished"] >= 14

  # The exemplar trace id in the fire details resolves at /debug/traces.
  exemplar = details["exemplar"]["trace_id"]
  httpd = make_http_server(svc)
  port = httpd.server_address[1]
  threading.Thread(target=httpd.serve_forever, daemon=True).start()
  try:
    _, found = _get_json(port, f"/debug/traces?id={exemplar}")
    assert found["traces"] and found["traces"][0]["trace_id"] == exemplar

    # /debug/incidents serves the ring: index, one bundle, 404s.
    _, listing = _get_json(port, "/debug/incidents")
    assert [e["id"] for e in listing["incidents"]] == \
        [e["id"] for e in index]
    assert listing["stats"]["captures"] == len(index)
    _, fetched = _get_json(port, f"/debug/incidents?id={entry['id']}")
    assert fetched["id"] == entry["id"]
    with pytest.raises(urllib.error.HTTPError) as err:
      _get(port, "/debug/incidents?id=incident-999999")
    assert err.value.code == 404

    # /debug/attrib serves the ledger with the conservation verdict.
    _, attrib = _get_json(port, "/debug/attrib")
    assert attrib["conservation"]["ok"] is True
    assert attrib["totals"]["requests"] == 14
    _, top1 = _get_json(port, "/debug/attrib?top=1")
    assert len(top1["cells"]) == 1 and top1["cells_total"] >= 2
    with pytest.raises(urllib.error.HTTPError) as err:
      _get(port, "/debug/attrib?top=x")
    assert err.value.code == 400
  finally:
    httpd.shutdown()

  # Dedup holds across a raw re-fire of a still-firing alert; the clear
  # edge releases it, and the next fire captures a fresh bundle.
  before = recorder.stats()["captures"]
  svc._on_slo_alert("latency_p99:scene_001", True, {})
  assert recorder.drain() == 0
  assert recorder.stats()["suppressed"] == 1
  clock.advance(11)  # the fast window drains: clears fire
  for i in range(6):
    svc.render_traced("scene_000", _pose(0.001 * i), timeout=60)
  tracker.alerts_firing()
  assert recorder.stats()["firing"] == []
  svc._on_slo_alert("latency_p99:scene_001", True, {})
  recorder.drain()
  assert recorder.stats()["captures"] == before + 1

  # /stats carries both blocks; /metrics carries both families.
  stats = svc.stats()
  assert stats["attrib"]["conservation"]["ok"] is True
  assert stats["incidents"]["captures"] == before + 1
  families = prom.parse_metrics_text(svc._render_metrics_text())
  assert attrib_mod.PREFIX + "requests_total" in families
  assert incident_mod.PREFIX + "captures_total" in families


def test_attrib_and_incident_endpoints_503_when_disabled():
  svc = RenderService(use_mesh=False, metrics_ttl_s=0.0)
  httpd = make_http_server(svc)
  port = httpd.server_address[1]
  threading.Thread(target=httpd.serve_forever, daemon=True).start()
  try:
    for path in ("/debug/attrib", "/debug/incidents"):
      with pytest.raises(urllib.error.HTTPError) as err:
        _get(port, path)
      assert err.value.code == 503
    with pytest.raises(RuntimeError, match="attribution disabled"):
      svc.attrib_snapshot()
  finally:
    httpd.shutdown()
    svc.close()


def test_incidents_require_slo():
  # slo=None disables the alert edges that trigger capture — a recorder
  # without them would be a black box that never records.
  with pytest.raises(ValueError, match="incidents require SLO"):
    RenderService(use_mesh=False, metrics_ttl_s=0.0, slo=None,
                  incidents=incident_mod.IncidentConfig(dir="/tmp/x"))
