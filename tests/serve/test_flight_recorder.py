"""Flight-recorder tests: native histograms, quantile SLOs, tsdb, shipper.

The acceptance pin for PR 10 lives here: a latency fault window on ONE
scene fires a per-scene p99 quantile-SLO alert visible simultaneously on
``/healthz`` (degraded with the quantile reason), ``/stats`` (the
``per_scene`` slo block), and ``/metrics`` (the native histogram with an
exemplar linking to a recorded trace id); the episode is queryable
afterward from ``/debug/tsdb`` history through the cluster router; and
every alert edge reaches a fake HTTP sink via the shipper — with the
sink down for part of the window and nothing lost (the disk spool drains
on recovery).

Everything else is fake-clock unit coverage: the exponential-bucket
math, exact merge (time buckets and backends), exemplar retention
through the router's pool aggregation (pinned against per-backend
ground truth), the tsdb ring's bounds, and the shipper's
retry/spool/segment accounting.
"""

import json
import math
import os
import threading
import urllib.error
import urllib.parse
import urllib.request

import numpy as np
import pytest

from mpi_vision_tpu.obs import hist as hist_mod
from mpi_vision_tpu.obs import prom
from mpi_vision_tpu.obs import ship as ship_mod
from mpi_vision_tpu.obs import tsdb as tsdb_mod
from mpi_vision_tpu.obs.events import EventLog, file_sink
from mpi_vision_tpu.obs.slo import SloConfig, SloTracker, verdict
from mpi_vision_tpu.obs.trace import Tracer
from mpi_vision_tpu.serve import RenderService, make_http_server
from mpi_vision_tpu.serve.cluster.router import Router
from mpi_vision_tpu.serve.metrics import ServeMetrics

H = W = 16
P = 4


class FakeClock:
  def __init__(self, t=1000.0):
    self.t = t

  def __call__(self):
    return self.t

  def advance(self, dt):
    self.t += dt
    return self.t


# --- native histogram core ------------------------------------------------


class TestNativeHistogram:

  def test_bucket_bounds_cover_the_index(self):
    for value in (1e-4, 0.003, 0.5, 1.0, 7.3, 120.0):
      idx = hist_mod.bucket_index(value)
      lo, hi = hist_mod.bucket_bounds(idx)
      assert lo < value <= hi or math.isclose(value, lo)

  def test_quantiles_track_ground_truth_within_bucket_width(self):
    rng = np.random.default_rng(7)
    values = rng.lognormal(-3.0, 1.0, 4000)
    h = hist_mod.NativeHistogram()
    for v in values:
      h.record(float(v))
    assert h.count == 4000
    for q in (0.5, 0.9, 0.99):
      est, true = h.quantile(q), float(np.quantile(values, q))
      # Exponential buckets at SCALE=4 are ~19% wide: the estimate must
      # land within one bucket of truth.
      assert abs(est - true) / true < 0.2, (q, est, true)

  def test_zero_and_negative_land_in_the_zero_bucket(self):
    h = hist_mod.NativeHistogram()
    h.record(0.0)
    h.record(-1.0)
    h.record(1.0)
    assert h.zero == 2 and h.count == 3
    assert h.quantile(0.25) == 0.0
    assert h.quantile(1.0) > 0.0

  def test_empty_quantile_is_none(self):
    assert hist_mod.NativeHistogram().quantile(0.99) is None
    assert hist_mod.quantile_of(None, 0.5) is None
    assert hist_mod.quantile_of({"count": 0}, 0.5) is None

  def test_extreme_values_clamp_instead_of_growing_without_bound(self):
    h = hist_mod.NativeHistogram()
    h.record(1e-300)
    h.record(1e300)
    assert set(h.buckets) == {hist_mod.MIN_IDX, hist_mod.MAX_IDX}

  def test_merge_equals_combined_recording(self):
    rng = np.random.default_rng(0)
    a_vals = rng.lognormal(-3, 0.5, 500)
    b_vals = rng.lognormal(-1, 0.5, 500)
    a, b, combined = (hist_mod.NativeHistogram() for _ in range(3))
    for v in a_vals:
      a.record(float(v))
      combined.record(float(v))
    for v in b_vals:
      b.record(float(v))
      combined.record(float(v))
    merged = hist_mod.merge([a.snapshot(), b.snapshot()])
    assert merged.count == combined.count
    assert merged.buckets == combined.buckets
    for q in (0.5, 0.99):
      assert merged.quantile(q) == pytest.approx(combined.quantile(q))

  def test_exemplars_newest_wins_and_merge_keeps_the_larger(self):
    a = hist_mod.NativeHistogram()
    a.record(0.1, exemplar="first")
    a.record(0.1, exemplar="second")  # same bucket: newest wins
    idx = hist_mod.bucket_index(0.1)
    assert a.exemplars[idx][0] == "second"
    b = hist_mod.NativeHistogram()
    b.record(0.105, exemplar="bigger")  # same bucket, larger value
    merged = hist_mod.merge([a.snapshot(), b.snapshot()])
    assert merged.exemplars[idx][0] == "bigger"

  def test_snapshot_is_json_ready_and_round_trips(self):
    h = hist_mod.NativeHistogram()
    for v in (0.01, 0.02, 0.5, 0.0):
      h.record(v, exemplar="tid")
    snap = json.loads(json.dumps(h.snapshot()))
    back = hist_mod.merge([snap])
    assert back.count == h.count and back.zero == h.zero
    assert back.quantile(0.5) == pytest.approx(h.quantile(0.5))

  def test_fraction_over_threshold(self):
    h = hist_mod.NativeHistogram()
    for _ in range(90):
      h.record(0.01)
    for _ in range(10):
      h.record(1.0)
    frac = h.fraction_over(0.1)
    assert 0.05 <= frac <= 0.15  # ~10%, within bucket interpolation


# --- exposition: render, parse, pool-merge --------------------------------


def _metrics_text(latencies, trace_ids=None):
  m = ServeMetrics()
  for i, lat in enumerate(latencies):
    m.record_request(lat, scene_id="s0",
                     trace_id=trace_ids[i] if trace_ids else None)
  return prom.render_serve_metrics(
      m.snapshot(cache_stats=None), m.latency_histogram())


class TestExposition:

  def test_nativehist_family_round_trips_with_exemplars(self):
    text = _metrics_text([0.01, 0.5], trace_ids=["aaa", "bbb"])
    fam = prom.parse_metrics_text(text)[
        "mpi_serve_request_latency_nativehist"]
    assert fam["type"] == "histogram"
    snaps = hist_mod.snapshots_from_samples(fam["samples"])
    snap = snaps[()]
    assert snap["count"] == 2
    assert hist_mod.quantile_of(snap, 0.5) == pytest.approx(0.01, rel=0.2)
    # Exemplar trace ids parsed off the bucket samples.
    tids = {ex[0] for ex in fam["exemplars"].values()}
    assert tids == {"aaa", "bbb"}

  def test_pool_aggregation_is_the_exact_bucket_merge(self):
    """The router-side contract (the PR's router-aggregation satellite):
    summing per-idx bucket samples across backends IS the exact
    histogram merge — pooled quantiles match the combined distribution's
    ground truth, unlike the non-additive gauges PR 7 had to drop."""
    rng = np.random.default_rng(3)
    fast = [float(v) for v in rng.lognormal(-4, 0.3, 400)]  # ~18 ms
    slow = [float(v) for v in rng.lognormal(-1, 0.3, 100)]  # ~370 ms
    t1 = _metrics_text(fast, trace_ids=["fast-tid"] * len(fast))
    t2 = _metrics_text(slow, trace_ids=["slow-tid"] * len(slow))
    agg = prom.aggregate_metrics_texts(
        [t1, t2], drop=hist_mod.NON_ADDITIVE_FAMILIES)
    fam = prom.parse_metrics_text(agg)[
        "mpi_serve_request_latency_nativehist"]
    snap = hist_mod.snapshots_from_samples(fam["samples"])[()]
    assert snap["count"] == 500
    combined = sorted(fast + slow)
    for q in (0.5, 0.9, 0.99):
      pooled = hist_mod.quantile_of(snap, q)
      true = float(np.quantile(combined, q))
      assert abs(pooled - true) / true < 0.2, (q, pooled, true)
    # The per-backend quantile gauges were dropped (summed p99s are
    # garbage) while the buckets merged.
    assert "mpi_serve_request_quantile_seconds" not in \
        prom.parse_metrics_text(agg)
    # Exemplars survive the merge; colliding buckets keep the larger
    # observation (the tail).
    assert 'trace_id="slow-tid"' in agg

  def test_serve_registry_quantile_gauges_agree_with_the_hist(self):
    m = ServeMetrics()
    for lat in (0.01, 0.02, 0.03, 0.4):
      m.record_request(lat)
    stats = m.snapshot(cache_stats=None)
    fams = prom.parse_metrics_text(
        prom.render_serve_metrics(stats, m.latency_histogram()))
    gauge = fams["mpi_serve_request_quantile_seconds"]["samples"]
    for q in hist_mod.QUANTILES:
      want = hist_mod.quantile_of(stats["hist"]["request"], q)
      got = gauge[("mpi_serve_request_quantile_seconds",
                   (("q", hist_mod.q_label(q)),))]
      assert got == pytest.approx(want)

  def test_strip_exemplars_yields_classic_format(self):
    """The default /metrics response must be parseable by a vanilla
    Prometheus text parser: no `#` after a sample value."""
    text = _metrics_text([0.01, 0.5], trace_ids=["aaa", "bbb"])
    assert " # {" in text
    plain = prom.strip_exemplars(text)
    assert " # {" not in plain
    # Same samples, exemplars gone.
    a = prom.parse_metrics_text(text)["mpi_serve_request_latency_nativehist"]
    b = prom.parse_metrics_text(plain)["mpi_serve_request_latency_nativehist"]
    assert a["samples"] == b["samples"]
    assert b["exemplars"] == {}

  def test_warp_pose_error_family_records_both_components(self):
    m = ServeMetrics()
    m.record_warp_pose_error(0.03, 1.5, trace_id="warp-tid")
    stats = m.snapshot(cache_stats=None)
    wpe = stats["hist"]["warp_pose_error"]
    assert wpe["trans"]["count"] == 1 and wpe["rot_deg"]["count"] == 1
    text = prom.render_serve_metrics(stats, m.latency_histogram())
    fam = prom.parse_metrics_text(text)["mpi_serve_edge_warp_pose_error"]
    comps = {dict(labels).get("component")
             for (name, labels) in fam["samples"]
             if name.endswith("_bucket")}
    assert comps == {"trans", "rot_deg"}
    assert 'trace_id="warp-tid"' in text


# --- quantile + per-scene SLO objectives ----------------------------------


def _qcfg(**kw):
  base = dict(fast_window_s=10.0, slow_window_s=60.0, bucket_s=1.0,
              min_requests=5, latency_threshold_s=0.25, quantile=0.99,
              per_scene=True)
  base.update(kw)
  return SloConfig(**base)


class TestQuantileSlo:

  def test_config_validation(self):
    with pytest.raises(ValueError, match="quantile"):
      SloConfig(quantile=1.5)
    with pytest.raises(ValueError, match="per_scene"):
      SloConfig(per_scene=True)  # needs a quantile
    assert SloConfig(quantile=0.99).quantile_name() == "latency_p99"
    assert SloConfig(quantile=0.999).quantile_name() == "latency_p99.9"
    assert SloConfig().quantile_name() is None

  def test_healthy_traffic_is_quiet(self):
    t = SloTracker(_qcfg(), clock=FakeClock())
    for _ in range(50):
      t.record(ok=True, latency_s=0.01, scene_id="a")
    assert t.alerts_firing() == []
    snap = t.snapshot()
    q99 = snap["objectives"]["latency_p99"]
    assert q99["fast"]["quantile_ms"] < 250
    assert snap["per_scene"]["a"]["alert"]["firing"] is False

  def test_single_hot_scene_fires_its_own_alert(self):
    clock = FakeClock()
    alerts = []
    t = SloTracker(_qcfg(), clock=clock,
                   on_alert=lambda n, f, d: alerts.append((n, f, d)))
    # 100 healthy requests on scene a, 20 slow ones on scene b: scene
    # b's p99 is deep over threshold while a's stays fine.
    for _ in range(100):
      t.record(ok=True, latency_s=0.01, scene_id="a")
    for _ in range(20):
      t.record(ok=True, latency_s=0.9, scene_id="b")
    firing = t.alerts_firing()
    assert "latency_p99:b" in firing
    assert "latency_p99:a" not in firing
    fire = next(a for a in alerts if a[0] == "latency_p99:b" and a[1])
    assert fire[2]["scene"] == "b" and fire[2]["fast_ms"] > 250
    snap = t.snapshot()
    assert snap["per_scene"]["b"]["alert"]["firing"] is True
    assert snap["per_scene"]["a"]["alert"]["firing"] is False
    assert snap["per_scene"]["b"]["slow"]["quantile_ms"] > 250
    # Recovery: the slow scene's samples age out of the fast window.
    clock.advance(11)
    for _ in range(10):
      t.record(ok=True, latency_s=0.01, scene_id="b")
    assert "latency_p99:b" not in t.alerts_firing()
    clear = next(a for a in alerts if a[0] == "latency_p99:b" and not a[1])
    assert clear[2]["scene"] == "b"

  def test_scene_whose_traffic_vanishes_still_clears(self):
    clock = FakeClock()
    t = SloTracker(_qcfg(), clock=clock)
    for _ in range(20):
      t.record(ok=True, latency_s=0.9, scene_id="b")
    assert "latency_p99:b" in t.alerts_firing()
    # No further traffic at all: once the fast window drains the alert
    # must clear on a bare scrape (an abandoned scene cannot page
    # forever).
    clock.advance(11)
    assert "latency_p99:b" not in t.alerts_firing()

  def test_min_requests_guards_idle_spikes(self):
    t = SloTracker(_qcfg(min_requests=50), clock=FakeClock())
    for _ in range(10):
      t.record(ok=True, latency_s=0.9, scene_id="b")
    assert t.alerts_firing() == []

  def test_window_memo_invalidates_on_new_data(self):
    """The merged quantile windows are memoized per (total, bucket) so a
    healthz probe doesn't pay the full ring-merge three times — but new
    data must invalidate it immediately, never serve a stale quantile."""
    clock = FakeClock()
    t = SloTracker(_qcfg(), clock=clock)
    for _ in range(20):
      t.record(ok=True, latency_s=0.01, scene_id="a")
    first = t.snapshot()["objectives"]["latency_p99"]["fast"]["quantile_ms"]
    assert t.snapshot()["objectives"]["latency_p99"]["fast"][
        "quantile_ms"] == first  # memo hit: same answer
    for _ in range(50):
      t.record(ok=True, latency_s=0.9, scene_id="a")
    after = t.snapshot()["objectives"]["latency_p99"]["fast"]["quantile_ms"]
    assert after > first  # new data visible at once

  def test_record_does_not_pay_quantile_merges_mid_bucket(self):
    """The hot-path contract: record() evaluates quantile alerts only on
    bucket rotation (merging every in-window histogram per bad request
    would tax the scheduler exactly during an incident); scrapes —
    alerts_firing/snapshot, i.e. healthz probes — evaluate them every
    time, so alert latency is bounded by min(bucket_s, scrape
    interval)."""
    t = SloTracker(_qcfg(), clock=FakeClock())
    for _ in range(20):
      t.record(ok=True, latency_s=0.9, scene_id="b")
    # Mid-bucket, no scrape yet: the quantile alert has not fired...
    assert not t._alerts["latency_p99"].firing
    # ...but the very next scrape fires it.
    assert "latency_p99" in t.alerts_firing()

  def test_scene_cardinality_is_bounded(self):
    t = SloTracker(_qcfg(), clock=FakeClock())
    from mpi_vision_tpu.obs import slo as slo_lib

    for i in range(slo_lib.PER_SCENE_CAP + 10):
      t.record(ok=True, latency_s=0.01, scene_id=f"scene_{i:03d}")
    snap = t.snapshot()
    assert len(snap["per_scene"]) <= slo_lib.PER_SCENE_CAP + 1
    assert "_other" in snap["per_scene"]

  def test_verdict_carries_quantile_and_per_scene_blocks(self):
    t = SloTracker(_qcfg(), clock=FakeClock())
    for _ in range(100):
      t.record(ok=True, latency_s=0.01, scene_id="a")
    for _ in range(20):
      t.record(ok=True, latency_s=0.9, scene_id="b")
    v = verdict(t.snapshot())
    q99 = v["objectives"]["latency_p99"]
    assert q99["quantile"] == 0.99 and q99["threshold_ms"] == 250.0
    assert q99["quantile_ms"] > 250 and q99["pass"] is False
    assert v["per_scene"]["failing"] == ["b"]
    assert v["per_scene"]["pass"] is False
    # The global verdict is judged by the global objectives; the
    # per-scene block carries its own pass.
    assert v["pass"] is False

  def test_registry_exposes_quantile_families(self):
    t = SloTracker(_qcfg(), clock=FakeClock())
    for _ in range(20):
      t.record(ok=True, latency_s=0.9, scene_id="b")
    snap = t.snapshot()
    fams = prom.parse_metrics_text(t.registry(snap).render())
    val = fams["mpi_slo_quantile_latency_seconds"]["samples"][
        ("mpi_slo_quantile_latency_seconds",
         (("slo", "latency_p99"), ("window", "fast")))]
    assert val == pytest.approx(
        snap["objectives"]["latency_p99"]["fast"]["quantile_ms"] / 1e3)
    assert fams["mpi_slo_quantile"]["samples"][
        ("mpi_slo_quantile", (("slo", "latency_p99"),))] == 0.99
    firing_scenes = fams["mpi_slo_scene_alerts_firing"]["samples"][
        ("mpi_slo_scene_alerts_firing", ())]
    assert firing_scenes == 1  # scene b's alert
    # The quantile gauges are registered non-additive (a pool must not
    # sum p99s).
    from mpi_vision_tpu.obs import slo as slo_lib

    assert "mpi_slo_quantile_latency_seconds" in \
        slo_lib.NON_ADDITIVE_FAMILIES


# --- tsdb ring ------------------------------------------------------------


class TestTsdb:

  def _recorder(self, clock, texts):
    """A recorder over a canned sequence of exposition texts."""
    state = {"i": 0}

    def collect():
      text = texts[min(state["i"], len(texts) - 1)]
      state["i"] += 1
      if isinstance(text, Exception):
        raise text
      return text

    return tsdb_mod.TsdbRecorder(collect, tsdb_mod.TsdbConfig(
        interval_s=1.0, max_points=4, max_series=8), clock=clock)

  def test_sample_query_window_and_point_bounds(self):
    clock = FakeClock(100.0)
    texts = [f"# TYPE m gauge\nm{{x=\"1\"}} {i}\n" for i in range(6)]
    rec = self._recorder(clock, texts)
    for _ in range(6):
      rec.sample()
      clock.advance(1.0)
    assert rec.families() == ["m"]
    series = rec.query("m")["series"]
    assert len(series) == 1
    # max_points=4: the ring kept only the newest 4 points.
    assert [p[1] for p in series[0]["points"]] == [2.0, 3.0, 4.0, 5.0]
    # recent window bounds further.
    recent = rec.query("m", recent_s=2.5)["series"][0]["points"]
    assert [p[1] for p in recent] == [4.0, 5.0]
    assert rec.query("m", points=1)["series"][0]["points"] == [[105.0, 5.0]]
    assert rec.query("absent")["series"] == []

  def test_series_cap_and_collector_errors_are_counted(self):
    clock = FakeClock()
    wide = "# TYPE m gauge\n" + "\n".join(
        f'm{{x="{i}"}} 1' for i in range(12)) + "\n"
    rec = self._recorder(clock, [wide, RuntimeError("boom")])
    rec.sample()
    stats = rec.stats()
    assert stats["series"] == 8 and stats["dropped_series"] == 4
    rec.sample()  # the collector raises: counted, never raised
    assert rec.stats()["sample_errors"] == 1

  def test_nan_and_inf_samples_never_enter_the_ring(self):
    """NaN ("no data" gauges like the idle quantile ones) and Inf must
    be skipped at record time: json.dumps would emit literal
    NaN/Infinity tokens — invalid JSON for every /debug/tsdb consumer
    and ship-sink collector."""
    clock = FakeClock()
    text = ("# TYPE m gauge\nm{x=\"nan\"} NaN\nm{x=\"inf\"} +Inf\n"
            "m{x=\"ok\"} 1\n")
    rec = self._recorder(clock, [text])
    rec.sample()
    q = rec.query("m")
    assert len(q["series"]) == 1
    assert q["series"][0]["labels"] == {"x": "ok"}
    json.dumps(q)  # must be valid JSON end to end
    json.dumps(rec.snapshot_since(None))

  def test_points_zero_returns_no_points_not_all(self):
    clock = FakeClock()
    rec = self._recorder(clock, ["# TYPE m gauge\nm 1\n"] * 2)
    rec.sample()
    rec.sample()
    assert rec.query("m", points=0)["series"] == []
    assert len(rec.query("m", points=1)["series"][0]["points"]) == 1

  def test_snapshot_since_is_an_incremental_cursor(self):
    clock = FakeClock(10.0)
    rec = self._recorder(clock, ["# TYPE m gauge\nm 1\n"] * 3)
    rec.sample()
    clock.advance(5)
    rec.sample()
    full = rec.snapshot_since(None)
    assert len(full["m"][0]["points"]) == 2
    inc = rec.snapshot_since(12.0)
    assert [p[1] for p in inc["m"][0]["points"]] == [1.0]
    assert rec.snapshot_since(99.0) == {}


# --- shipper --------------------------------------------------------------


class FlakySink:
  """A sink transport that is down until told otherwise."""

  def __init__(self, down=True):
    self.down = down
    self.bodies: list[dict] = []

  def post(self, url, body, timeout):
    if self.down:
      raise ConnectionError("sink down")
    self.bodies.append(json.loads(body))
    return 200


def _shipper(tmp_path, sink, clock, **cfg_kw):
  cfg = ship_mod.ShipConfig(url="http://sink.invalid/ingest",
                            spool_dir=str(tmp_path / "spool"), **cfg_kw)
  return ship_mod.TelemetryShipper(cfg, transport=sink, clock=clock,
                                   sleep=lambda s: None)


class TestShipper:

  def test_outage_spools_then_recovery_drains_in_order(self, tmp_path):
    clock = FakeClock()
    sink = FlakySink(down=True)
    shipper = _shipper(tmp_path, sink, clock)
    shipper.note_alert({"kind": "slo_alert", "slo": "x", "firing": True})
    shipper.tick()  # down: batch spooled
    clock.advance(1)
    shipper.note_alert({"kind": "slo_alert", "slo": "x", "firing": False})
    shipper.tick()  # still down: second batch spooled
    stats = shipper.stats()
    assert stats["spooled"] == 2 and stats["spool_files"] == 2
    assert stats["batches_shipped"] == 0 and stats["post_failures"] > 0
    sink.down = False
    shipper.tick()  # recovery: the spool drains oldest-first
    stats = shipper.stats()
    assert stats["spool_files"] == 0 and stats["batches_shipped"] == 2
    edges = [e for b in sink.bodies for it in b["items"]
             for e in it["edges"]]
    assert [e["firing"] for e in edges] == [True, False]  # order kept

  def test_spool_budget_drops_oldest(self, tmp_path):
    clock = FakeClock()
    sink = FlakySink(down=True)
    shipper = _shipper(tmp_path, sink, clock, spool_budget_bytes=400)
    for i in range(5):
      shipper.note_alert({"kind": "slo_alert", "slo": f"pad{i}" * 20,
                          "firing": True})
      shipper.tick()
    stats = shipper.stats()
    assert stats["spool_dropped"] >= 1
    assert stats["spool_bytes"] <= 400

  def test_oversized_batch_is_never_evicted_by_its_own_spool(self, tmp_path):
    """A batch larger than the whole spool budget must survive its own
    budget sweep: _spool returning True advances the tsdb cursor, so
    evicting the just-written file would silently lose that window
    (bounded overshoot beats silent loss)."""
    clock = FakeClock()
    sink = FlakySink(down=True)
    shipper = _shipper(tmp_path, sink, clock, spool_budget_bytes=64)
    shipper.note_alert({"kind": "slo_alert", "pad": "x" * 500})
    shipper.tick()
    stats = shipper.stats()
    assert stats["spool_files"] == 1 and stats["spool_dropped"] == 0
    sink.down = False
    shipper.tick()
    assert shipper.stats()["spool_files"] == 0
    assert any(it["kind"] == "slo_alert_edges"
               for b in sink.bodies for it in b.get("items", []))

  def test_without_spool_failed_batches_drop_counted(self, tmp_path):
    clock = FakeClock()
    sink = FlakySink(down=True)
    cfg = ship_mod.ShipConfig(url="http://sink.invalid/i", spool_dir=None)
    shipper = ship_mod.TelemetryShipper(cfg, transport=sink, clock=clock,
                                        sleep=lambda s: None)
    shipper.note_alert({"kind": "slo_alert"})
    shipper.tick()
    assert shipper.stats()["spool_dropped"] == 1

  def test_cursor_holds_when_batch_neither_ships_nor_spools(self):
    """Spool off + sink down: the batch is gone, but its tsdb points
    still sit in the ring — the cursor must NOT advance, so the next
    successful tick re-ships them for free instead of stranding up to a
    whole interval of history."""
    clock = FakeClock(0.0)
    rec = tsdb_mod.TsdbRecorder(lambda: "# TYPE m gauge\nm 1\n",
                                tsdb_mod.TsdbConfig(interval_s=1.0),
                                clock=clock)
    rec.sample()  # point at ts=0.0
    sink = FlakySink(down=True)
    cfg = ship_mod.ShipConfig(url="http://x/i", spool_dir=None)
    shipper = ship_mod.TelemetryShipper(cfg, tsdb=rec, transport=sink,
                                        clock=clock, sleep=lambda s: None)
    shipper.tick()  # down, no spool: dropped — cursor must hold
    sink.down = False
    shipper.tick()
    shipped_ts = [p[0] for b in sink.bodies for it in b.get("items", [])
                  if it["kind"] == "tsdb"
                  for series in it["families"]["m"]
                  for p in series["points"]]
    assert shipped_ts == [0.0]  # recovered from the ring, not lost

  def test_rotated_segments_ship_and_delete(self, tmp_path):
    clock = FakeClock()
    sink = FlakySink(down=False)
    events_path = str(tmp_path / "events.jsonl")
    # Tiny rotation budget: a few emits rotate segments out.
    sink_fn = file_sink(events_path, max_bytes=64, keep=2)
    log = EventLog(clock=clock, sink=sink_fn)
    for i in range(12):
      log.emit("tick", i=i, pad="x" * 40)
    assert sink_fn.rotations >= 2
    assert sink_fn.segments_dropped >= 1  # rotated off the end, unshipped
    snap = log.snapshot()
    assert snap["retention"]["rotations"] == sink_fn.rotations
    assert snap["retention"]["segments_dropped"] == \
        sink_fn.segments_dropped
    cfg = ship_mod.ShipConfig(url="http://sink.invalid/i",
                              events_path=events_path, events_keep=2)
    shipper = ship_mod.TelemetryShipper(cfg, transport=sink, clock=clock,
                                        sleep=lambda s: None)
    pending = shipper.pending_segments()
    assert pending >= 1
    shipper.tick()
    assert shipper.stats()["segments_shipped"] == pending
    assert shipper.pending_segments() == 0  # delivered => deleted
    segs = [b for b in sink.bodies if b.get("kind") == "mpi_events_segment"]
    assert len(segs) == pending
    assert all("tick" in s["content"] for s in segs)
    # The sink goes down: segments survive on disk for the next tick.
    for i in range(12):
      log.emit("tick", i=i, pad="y" * 40)
    sink.down = True
    before = shipper.pending_segments()
    assert before >= 1
    shipper.tick()
    assert shipper.pending_segments() == before
    sink_fn.close()

  def test_spool_sequence_survives_a_process_restart(self, tmp_path):
    """A restarted shipper must resume the spool sequence PAST the
    previous process's files: restarting at 1 would os.replace over
    them — losing exactly the telemetry the spool exists to preserve —
    and break the oldest-first drain order."""
    clock = FakeClock()
    sink = FlakySink(down=True)
    first = _shipper(tmp_path, sink, clock)
    first.note_alert({"kind": "slo_alert", "run": 1})
    first.tick()
    assert first.stats()["spool_files"] == 1
    # "Restart": a fresh shipper over the same spool dir.
    second = _shipper(tmp_path, sink, clock)
    second.note_alert({"kind": "slo_alert", "run": 2})
    second.tick()
    assert second.stats()["spool_files"] == 2  # nothing overwritten
    sink.down = False
    second.tick()
    runs = [e["run"] for b in sink.bodies for it in b["items"]
            for e in it["edges"]]
    assert runs == [1, 2]  # both survived, drained oldest-first

  def test_segments_are_claimed_before_shipping(self, tmp_path):
    """The rotation TOCTOU guard: a sink-down tick atomically renames
    rotated segments OUT of rotation's FILE.N namespace before any POST,
    so a rotation that lands mid-outage can neither overwrite a segment
    being shipped nor be deleted in its place; everything — claimed and
    newly rotated — arrives once the sink recovers."""
    clock = FakeClock()
    sink = FlakySink(down=True)
    events_path = str(tmp_path / "events.jsonl")
    sink_fn = file_sink(events_path, max_bytes=64, keep=2)
    log = EventLog(clock=clock, sink=sink_fn)
    for i in range(8):
      log.emit("gen1", i=i, pad="x" * 40)
    cfg = ship_mod.ShipConfig(url="http://sink.invalid/i",
                              events_path=events_path, events_keep=2)
    shipper = ship_mod.TelemetryShipper(cfg, transport=sink, clock=clock,
                                        sleep=lambda s: None)
    first_wave = shipper.pending_segments()
    assert first_wave >= 1
    shipper.tick()  # sink down: segments CLAIMED (renamed), not lost
    assert shipper.pending_segments() == first_wave
    assert not any(os.path.exists(f"{events_path}.{i}")
                   for i in (1, 2))  # rotation's slots are free again
    # Rotation keeps going during the outage — new segments appear in
    # the now-free slots without touching the claimed ones.
    for i in range(8):
      log.emit("gen2", i=i, pad="y" * 40)
    assert shipper.pending_segments() > first_wave
    sink.down = False
    shipper.tick()
    assert shipper.pending_segments() == 0
    contents = "".join(b["content"] for b in sink.bodies
                       if b.get("kind") == "mpi_events_segment")
    assert "gen1" in contents and "gen2" in contents  # nothing lost
    sink_fn.close()

  def test_garbled_sink_response_is_retried_and_spooled(self, tmp_path,
                                                        monkeypatch):
    """A half-dead sink raising http.client.HTTPException (BadStatusLine,
    IncompleteRead) must look like a down sink — retried then spooled —
    not escape as a tick_error that silently drops the drained edges."""
    import http.client

    # The real transport maps HTTPException -> ConnectionError (the
    # router-transport contract).
    monkeypatch.setattr(
        "urllib.request.urlopen",
        lambda req, timeout: (_ for _ in ()).throw(
            http.client.BadStatusLine("garbage")))
    with pytest.raises(ConnectionError):
      ship_mod.HttpPostTransport().post("http://x/i", b"{}", 1.0)

    # End to end, even a transport that BREAKS the contract and raises
    # something else: the arc still retries and spools, never drops.
    class GarbledSink:
      def post(self, url, body, timeout):
        raise http.client.BadStatusLine("garbage")

    clock = FakeClock()
    cfg = ship_mod.ShipConfig(url="http://x/i",
                              spool_dir=str(tmp_path / "spool"))
    shipper = ship_mod.TelemetryShipper(
        cfg, transport=GarbledSink(), clock=clock, sleep=lambda s: None)
    shipper.note_alert({"kind": "slo_alert"})
    shipper.tick()
    assert shipper.stats()["spooled"] == 1
    assert shipper.stats()["tick_errors"] == 0

  def test_claim_backlog_is_bounded_during_a_long_outage(self, tmp_path):
    """A sink outage under a busy event stream must not grow FILE.ship.*
    without bound (claiming frees rotation's slots, so the events_keep
    disk bound no longer applies): past MAX_CLAIMED_SEGMENTS the oldest
    claims drop, counted."""
    clock = FakeClock()
    sink = FlakySink(down=True)
    events_path = str(tmp_path / "events.jsonl")
    sink_fn = file_sink(events_path, max_bytes=64, keep=2)
    log = EventLog(clock=clock, sink=sink_fn)
    cfg = ship_mod.ShipConfig(url="http://sink.invalid/i",
                              events_path=events_path, events_keep=2)
    shipper = ship_mod.TelemetryShipper(cfg, transport=sink, clock=clock,
                                        sleep=lambda s: None)
    for round_i in range(ship_mod.MAX_CLAIMED_SEGMENTS):
      for i in range(6):
        log.emit("tick", r=round_i, i=i, pad="z" * 40)
      shipper.tick()  # down: claims whatever rotated this round
    assert shipper.pending_segments() <= ship_mod.MAX_CLAIMED_SEGMENTS
    assert shipper.stats()["segments_dropped"] >= 1
    sink_fn.close()

  def test_tsdb_backlog_drains_across_ticks_without_loss(self, tmp_path):
    """More points per series than one batch carries: truncation keeps
    the OLDEST and the cursor follows what shipped, so the backlog
    drains over consecutive ticks — nothing stranded behind the
    cursor."""
    clock = FakeClock(0.0)
    rec = tsdb_mod.TsdbRecorder(lambda: "# TYPE m gauge\nm 1\n",
                                tsdb_mod.TsdbConfig(interval_s=1.0,
                                                    max_points=256),
                                clock=clock)
    for _ in range(10):
      rec.sample()
      clock.advance(1.0)
    sink = FlakySink(down=False)
    cfg = ship_mod.ShipConfig(url="http://x/i",
                              spool_dir=str(tmp_path / "spool"))
    shipper = ship_mod.TelemetryShipper(cfg, tsdb=rec, transport=sink,
                                        clock=clock, sleep=lambda s: None)
    # Force tiny batches via the snapshot bound.
    original = rec.snapshot_since
    rec.snapshot_since = lambda since, max_points_per_series=64: original(
        since, max_points_per_series=3)
    for _ in range(5):
      shipper.tick()
    shipped = [p[0] for b in sink.bodies for it in b.get("items", [])
               if it["kind"] == "tsdb"
               for series in it["families"]["m"]
               for p in series["points"]]
    assert shipped == [float(i) for i in range(10)]  # all, in order

  def test_tsdb_cursor_tracks_shipped_points_not_the_clock(self, tmp_path):
    """The cursor advances to the max point timestamp actually shipped —
    a clock-read cursor ahead of the recorder's timestamps would skip
    every later sample forever."""
    rec_clock = FakeClock(10.0)
    texts = ["# TYPE m gauge\nm 1\n"]
    rec = tsdb_mod.TsdbRecorder(lambda: texts[0],
                                tsdb_mod.TsdbConfig(interval_s=1.0),
                                clock=rec_clock)
    rec.sample()  # point at ts=10.0
    # The shipper's wall clock runs far AHEAD of the recorder's stamps.
    ship_clock = FakeClock(1000.0)
    sink = FlakySink(down=False)
    cfg = ship_mod.ShipConfig(url="http://x/i",
                              spool_dir=str(tmp_path / "spool"))
    shipper = ship_mod.TelemetryShipper(cfg, tsdb=rec, transport=sink,
                                        clock=ship_clock,
                                        sleep=lambda s: None)
    shipper.tick()
    rec_clock.advance(5)
    rec.sample()  # point at ts=15.0 — BELOW the shipper's wall clock
    shipper.tick()
    shipped_ts = [p[0] for b in sink.bodies for it in b.get("items", [])
                  if it["kind"] == "tsdb"
                  for series in it["families"]["m"]
                  for p in series["points"]]
    assert shipped_ts == [10.0, 15.0]  # nothing skipped, nothing doubled

  def test_retry_policy_counts_and_registry_zeros(self, tmp_path):
    clock = FakeClock()
    sink = FlakySink(down=True)
    shipper = _shipper(tmp_path, sink, clock)
    shipper.note_alert({"kind": "slo_alert"})
    shipper.tick()
    stats = shipper.stats()
    # RetryPolicy default here: 2 retries => 3 attempts per arc.
    assert stats["posts"] == 3 and stats["retries"] == 2
    fams = prom.parse_metrics_text(ship_mod.registry(stats).render())
    assert fams["mpi_obs_ship_failures_total"]["samples"][
        ("mpi_obs_ship_failures_total", ())] == 3
    zeros = prom.parse_metrics_text(ship_mod.registry(None).render())
    assert zeros["mpi_obs_ship_batches_total"]["samples"][
        ("mpi_obs_ship_batches_total", ())] == 0


# --- router: pooled quantiles + tsdb fan-out (fake transport) -------------


class FakeBackendTransport:
  """Canned per-backend GET responses keyed by (address, path)."""

  def __init__(self, responses):
    self.responses = responses  # {address: {path: payload}}

  def request(self, method, url, body=None, headers=None, timeout=30.0):
    parsed = urllib.parse.urlsplit(url)
    path = parsed.path + ("?" + parsed.query if parsed.query else "")
    backend = self.responses.get(parsed.netloc)
    if backend is None:
      raise ConnectionError("refused")
    payload = backend.get(path)
    if payload is None:
      payload = {"error": f"unknown path {path}"}
    if isinstance(payload, str):
      return 200, {"Content-Type": "text/plain"}, payload.encode()
    return 200, {"Content-Type": "application/json"}, \
        json.dumps(payload).encode()


def test_router_pools_native_histograms_against_ground_truth():
  """The router-aggregation satellite: pooled quantiles are bucket-merged
  across backends (pinned against the combined distribution's ground
  truth) and exemplar trace ids survive the merge."""
  rng = np.random.default_rng(11)
  lat1 = [float(v) for v in rng.lognormal(-4.0, 0.4, 300)]
  lat2 = [float(v) for v in rng.lognormal(-0.5, 0.4, 60)]
  transport = FakeBackendTransport({
      "h1:1": {"/metrics?exemplars=1":
               _metrics_text(lat1, ["t1"] * len(lat1))},
      "h2:2": {"/metrics?exemplars=1":
               _metrics_text(lat2, ["t2-slow"] * len(lat2))},
  })
  router = Router({"b1": "h1:1", "b2": "h2:2"}, transport=transport,
                  metrics_ttl_s=0.0)
  text = router.metrics_text()
  fams = prom.parse_metrics_text(text)
  combined = sorted(lat1 + lat2)
  for q in hist_mod.QUANTILES:
    pooled = fams["mpi_cluster_request_quantile_seconds"]["samples"][
        ("mpi_cluster_request_quantile_seconds",
         (("q", hist_mod.q_label(q)),))]
    true = float(np.quantile(combined, q))
    assert abs(pooled - true) / true < 0.2, (q, pooled, true)
  # Bucket counts merged exactly (counts add to the combined total)...
  snap = hist_mod.snapshots_from_samples(
      fams["mpi_serve_request_latency_nativehist"]["samples"])[()]
  assert snap["count"] == len(combined)
  # ...the per-backend quantile gauges were dropped, not summed...
  assert "mpi_serve_request_quantile_seconds" not in fams
  # ...and the slow backend's exemplar survived the merge.
  assert 'trace_id="t2-slow"' in text
  router.close()


def test_router_tsdb_fanout_merges_backends():
  payload = {"family": "m", "series": [
      {"name": "m", "labels": {}, "points": [[1.0, 2.0]]}]}
  transport = FakeBackendTransport({
      "h1:1": {"/debug/tsdb?family=m&recent=60": payload},
      "h2:2": {},  # backend without the endpoint: its error rides along
  })
  router = Router({"b1": "h1:1", "b2": "h2:2"}, transport=transport)
  snap = router.tsdb_snapshot(family="m", recent_s=60)
  assert snap["backends"]["b1"] == payload
  assert "error" in snap["backends"]["b2"]
  assert snap["router"] is None  # no router-side ring configured
  router.close()


# --- THE acceptance pin ---------------------------------------------------


def _get_json(port, path):
  with urllib.request.urlopen(
      f"http://127.0.0.1:{port}{path}", timeout=30) as resp:
    return resp.status, json.loads(resp.read())


def test_flight_recorder_acceptance(tmp_path):
  """The full loop: a latency fault window on ONE scene fires a
  per-scene p99 quantile-SLO alert visible on /healthz, /stats, and
  /metrics (native histogram + exemplar linking to a recorded trace id),
  is queryable afterward from /debug/tsdb history through the router,
  and arrives at a fake HTTP sink via the shipper — with the sink down
  for part of the window and no telemetry lost (the spool drains on
  recovery)."""
  clock = FakeClock()
  tracker = SloTracker(_qcfg(), clock=clock)
  svc = RenderService(use_mesh=False, slo=tracker, tracer=Tracer(),
                      metrics_ttl_s=0.0)
  recorder = tsdb_mod.TsdbRecorder(
      svc._render_metrics_text,
      tsdb_mod.TsdbConfig(interval_s=1.0), clock=clock)
  svc.tsdb = recorder
  sink = FlakySink(down=False)
  shipper = ship_mod.TelemetryShipper(
      ship_mod.ShipConfig(url="http://sink.invalid/ingest",
                          spool_dir=str(tmp_path / "spool")),
      tsdb=recorder, transport=sink, clock=clock, sleep=lambda s: None)
  svc.shipper = shipper
  svc.add_synthetic_scenes(2, height=H, width=W, planes=P)
  svc.warmup()
  httpd = make_http_server(svc)
  port = httpd.server_address[1]
  threading.Thread(target=httpd.serve_forever, daemon=True).start()
  try:
    # One REAL render over HTTP: its X-Trace-Id is the recorded trace
    # the exemplar must link to.
    body = json.dumps({"scene_id": "scene_001",
                       "pose": np.eye(4).tolist()}).encode()
    req = urllib.request.Request(f"http://127.0.0.1:{port}/render",
                                 data=body,
                                 headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as resp:
      tid = resp.headers["X-Trace-Id"]
    assert svc.tracer.find(tid)  # the id resolves to a recorded trace

    # Phase 1 — healthy traffic on both scenes; one tsdb sample. 120
    # good samples per scene keep the window's p99 inside the healthy
    # mass even though the one real render above (arbitrarily slow on a
    # loaded CI box) is in the same window.
    for _ in range(120):
      svc.metrics.record_request(0.01, scene_id="scene_000")
      svc.metrics.record_request(0.01, scene_id="scene_001", trace_id=tid)
    assert tracker.alerts_firing() == []
    recorder.sample()
    shipper.tick()  # sink up: baseline batch lands
    baseline_batches = shipper.stats()["batches_shipped"]
    clock.advance(2)

    # Phase 2 — the fault window: ONLY scene_001 turns slow. Its p99
    # blows through the 250 ms threshold; scene_000 stays healthy.
    sink.down = True  # ...and the telemetry sink goes down with it
    for _ in range(60):
      svc.metrics.record_request(0.9, scene_id="scene_001", trace_id=tid)
      svc.metrics.record_request(0.01, scene_id="scene_000")
    firing = tracker.alerts_firing()
    assert "latency_p99:scene_001" in firing
    assert "latency_p99:scene_000" not in firing
    recorder.sample()
    shipper.tick()  # sink down: the batch (with the FIRE edge) spools
    assert shipper.stats()["spooled"] >= 1

    # Surface 1: /healthz — degraded, with the per-scene quantile
    # reason.
    status, health = _get_json(port, "/healthz")
    assert status == 200 and health["status"] == "degraded"
    assert "latency_p99:scene_001" in health["reason"]
    assert "latency_p99:scene_001" in health["slo_alerts_firing"]

    # Surface 2: /stats — the per_scene slo block shows the hot scene.
    _, stats = _get_json(port, "/stats")
    per_scene = stats["slo"]["per_scene"]
    assert per_scene["scene_001"]["alert"]["firing"] is True
    assert per_scene["scene_001"]["fast"]["quantile_ms"] > 250
    assert per_scene["scene_000"]["alert"]["firing"] is False

    # Surface 3: /metrics — the native histogram family carries the
    # fault window, with an exemplar linking to the recorded trace
    # (?exemplars=1; the default response strips them for vanilla
    # Prometheus parsers).
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=30) as resp:
      plain = resp.read().decode()
    assert " # {" not in plain  # classic-format safe by default
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics?exemplars=1", timeout=30) as resp:
      mtext = resp.read().decode()
    fams = prom.parse_metrics_text(mtext)
    snap = hist_mod.snapshots_from_samples(
        fams["mpi_serve_request_latency_nativehist"]["samples"])[()]
    assert hist_mod.quantile_of(snap, 0.99) > 0.25
    assert f'trace_id="{tid}"' in mtext
    assert fams["mpi_slo_scene_alerts_firing"]["samples"][
        ("mpi_slo_scene_alerts_firing", ())] >= 1

    # Phase 3 — recovery: the fault ages out; the alert clears.
    clock.advance(11)
    for _ in range(20):
      svc.metrics.record_request(0.01, scene_id="scene_001", trace_id=tid)
    assert "latency_p99:scene_001" not in tracker.alerts_firing()
    recorder.sample()

    # The episode is queryable AFTERWARD from /debug/tsdb — directly...
    _, ts = _get_json(
        port, "/debug/tsdb?family=mpi_slo_quantile_latency_seconds")
    fast_series = next(
        s for s in ts["series"]
        if s["labels"] == {"slo": "latency_p99", "window": "fast"})
    values = [p[1] for p in fast_series["points"]]
    assert len(values) == 3
    assert values[1] > 0.25 > values[0]  # the spike is in the history
    assert values[2] < 0.25              # ...and so is the recovery

    # ...and through the router (one query reads fleet history).
    router = Router({"b0": f"127.0.0.1:{port}"}, metrics_ttl_s=0.0)
    try:
      rsnap = router.tsdb_snapshot(
          family="mpi_slo_quantile_latency_seconds")
      rvals = [p[1] for s in rsnap["backends"]["b0"]["series"]
               if s["labels"] == {"slo": "latency_p99", "window": "fast"}
               for p in s["points"]]
      assert rvals == values
      # The router's pooled exposition also carries the fleet p99 from
      # the merged native histogram.
      rfams = prom.parse_metrics_text(router.metrics_text())
      assert ("mpi_cluster_request_quantile_seconds",
              (("q", "0.99"),)) in \
          rfams["mpi_cluster_request_quantile_seconds"]["samples"]
    finally:
      router.close()

    # The sink recovers: the spool drains and NOTHING was lost — the
    # fire AND clear edges (and tsdb items) all reach the sink.
    sink.down = False
    shipper.tick()
    stats = shipper.stats()
    assert stats["spool_files"] == 0
    assert stats["batches_shipped"] > baseline_batches
    edges = [e for b in sink.bodies for it in b.get("items", [])
             if it["kind"] == "slo_alert_edges" for e in it["edges"]]
    scene_edges = [(e["firing"]) for e in edges
                   if e["slo"] == "latency_p99:scene_001"]
    assert scene_edges == [True, False]  # fire then clear, in order
    assert any(it["kind"] == "tsdb" for b in sink.bodies
               for it in b.get("items", []))
    assert os.listdir(tmp_path / "spool") == []
  finally:
    httpd.shutdown()
    svc.close()


# --- PR 12 satellites: tsdb compaction + SLO exemplars --------------------


class TestTsdbCompaction:

  def _recorder(self, clock, compact_after_s=4.0, stride=4, max_points=64):
    state = {"i": 0}

    def collect():
      state["i"] += 1
      return f"# TYPE m gauge\nm {state['i']}\n"

    return tsdb_mod.TsdbRecorder(collect, tsdb_mod.TsdbConfig(
        interval_s=1.0, max_points=max_points,
        compact_after_s=compact_after_s, compact_stride=stride),
        clock=clock)

  def test_old_points_thin_to_the_stride_recent_stay_full(self):
    clock = FakeClock(100.0)
    rec = self._recorder(clock, compact_after_s=4.0, stride=4)
    for _ in range(16):
      rec.sample()
      clock.advance(1.0)
    pts = rec.query("m")["series"][0]["points"]
    ts = [p[0] for p in pts]
    cutoff = max(ts) - 4.0  # the LAST sample's compaction cutoff
    old = [t for t in ts if t < cutoff]
    recent = [t for t in ts if t >= cutoff]
    # Recent window keeps every 1s sample; the old tail is >= stride*interval
    # apart (thinned, not evicted — the oldest timestamp survives).
    assert len(recent) >= 3
    assert min(ts) == 100.0
    assert all(b - a >= 4.0 for a, b in zip(old, old[1:]))
    assert rec.stats()["compacted_points"] > 0
    # Idempotent: re-sampling does not re-thin already-compacted history
    # below the stride spacing.
    before = [p[0] for p in rec.query("m")["series"][0]["points"]
              if p[0] < cutoff]
    rec.sample()
    after = [p[0] for p in rec.query("m")["series"][0]["points"]
             if p[0] < cutoff]
    assert before[0] == after[0]

  def test_compaction_extends_history_span_in_the_same_budget(self):
    # max_points comfortably above the stride (the realistic shape —
    # 512 vs 8 in production): the sweep cadence is amortized to one
    # per stride samples, and the thinned tail still outlives the
    # plain ring by ~stride x.
    clock_a, clock_b = FakeClock(100.0), FakeClock(100.0)
    plain = self._recorder(clock_a, compact_after_s=None, max_points=16)
    compact = self._recorder(clock_b, compact_after_s=4.0, stride=4,
                             max_points=16)
    for _ in range(64):
      plain.sample()
      compact.sample()
      clock_a.advance(1.0)
      clock_b.advance(1.0)
    span = lambda r: (lambda p: p[-1][0] - p[0][0])(
        r.query("m")["series"][0]["points"])
    assert span(compact) >= 2 * span(plain)  # same budget, longer history
    assert len(compact.query("m")["series"][0]["points"]) <= 16

  def test_config_validation(self):
    with pytest.raises(ValueError, match="compact_after_s"):
      tsdb_mod.TsdbConfig(compact_after_s=0)
    with pytest.raises(ValueError, match="compact_stride"):
      tsdb_mod.TsdbConfig(compact_after_s=10.0, compact_stride=1)


class TestSloExemplars:

  def test_per_scene_snapshot_carries_the_worst_offender_trace(self):
    t = SloTracker(_qcfg(), clock=FakeClock())
    for i in range(30):
      t.record(ok=True, latency_s=0.01, scene_id="a", trace_id=f"t{i:02d}")
    t.record(ok=True, latency_s=0.7, scene_id="a", trace_id="worst")
    t.record(ok=True, latency_s=0.3, scene_id="a", trace_id="meh")
    snap = t.snapshot()
    ex = snap["per_scene"]["a"]["slow"]["exemplar"]
    assert ex["trace_id"] == "worst"
    assert ex["value_ms"] == pytest.approx(700.0)
    # The global quantile objective carries it too.
    assert snap["objectives"]["latency_p99"]["slow"][
        "exemplar"]["trace_id"] == "worst"

  def test_quantile_alert_fire_edge_links_the_exemplar(self):
    alerts = []
    t = SloTracker(_qcfg(), clock=FakeClock(),
                   on_alert=lambda n, f, d: alerts.append((n, f, d)))
    for i in range(20):
      t.record(ok=True, latency_s=0.9, scene_id="b", trace_id=f"bad{i}")
    assert "latency_p99:b" in t.alerts_firing()
    fire = next(d for n, f, d in alerts if n == "latency_p99:b" and f)
    assert fire["exemplar"]["trace_id"].startswith("bad")

  def test_no_trace_ids_means_no_exemplar_key(self):
    t = SloTracker(_qcfg(), clock=FakeClock())
    for _ in range(20):
      t.record(ok=True, latency_s=0.01, scene_id="a")
    snap = t.snapshot()
    assert "exemplar" not in snap["per_scene"]["a"]["slow"]


# --- ship-sink collector --------------------------------------------------


class TestShipSink:
  """The collector side (``ship-sink`` CLI engine): the shipper's
  off-host leg driven end to end over real localhost HTTP — no
  hand-rolled test sink."""

  @pytest.fixture()
  def sink_server(self, tmp_path):
    server, sink = ship_mod.make_sink_server(str(tmp_path / "batches"))
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    yield f"http://127.0.0.1:{server.server_address[1]}", sink, \
        str(tmp_path / "batches")
    server.shutdown()
    server.server_close()

  def test_shipper_delivers_batches_into_the_directory(self, tmp_path,
                                                       sink_server):
    url, sink, directory = sink_server
    cfg = ship_mod.ShipConfig(url=url + "/ingest", timeout_s=5.0,
                              spool_dir=str(tmp_path / "spool"))
    shipper = ship_mod.TelemetryShipper(cfg, clock=FakeClock(),
                                        sleep=lambda s: None)
    shipper.note_alert({"kind": "slo_alert", "slo": "x", "firing": True})
    shipper.tick()
    shipper.note_alert({"kind": "slo_alert", "slo": "x", "firing": False})
    shipper.tick()
    assert shipper.stats()["batches_shipped"] == 2
    names = sorted(os.listdir(directory))
    assert names == ["batch-00000001.json", "batch-00000002.json"]
    # Stored bodies are the shipper's own batch JSON, byte for byte
    # parseable, in delivery order.
    edges = []
    for name in names:
      with open(os.path.join(directory, name)) as f:
        batch = json.load(f)
      edges += [e["firing"] for item in batch["items"]
                for e in item.get("edges", [])]
    assert edges == [True, False]
    assert sink.stats()["received"] == 2 and sink.stats()["rejected"] == 0

  def test_sink_rejects_garbage_and_numbering_resumes(self, tmp_path,
                                                      sink_server):
    url, sink, directory = sink_server
    bad = urllib.request.Request(url + "/ingest", data=b"not json{",
                                 method="POST")
    with pytest.raises(urllib.error.HTTPError) as err:
      urllib.request.urlopen(bad, timeout=5)
    assert err.value.code == 400
    assert sink.stats()["rejected"] == 1
    ok = urllib.request.Request(url + "/ingest", data=b'{"items": []}',
                                method="POST")
    with urllib.request.urlopen(ok, timeout=5) as resp:
      assert json.loads(resp.read())["ok"] is True
    with urllib.request.urlopen(url + "/healthz", timeout=5) as resp:
      health = json.loads(resp.read())
    assert health["status"] == "ok" and health["received"] == 1
    # A fresh sink over the same directory continues the numbering —
    # restarts never overwrite delivered telemetry.
    resumed = ship_mod.ShipSink(directory)
    assert resumed.stats()["next_seq"] == 2
