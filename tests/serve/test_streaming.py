"""Streaming render pipeline tests: async engine API, overlap bookkeeping,
out-of-order completion, and pipelined-vs-blocking parity.

The tentpole invariants of the PR-7 rebuild:

  * the streaming path (``submit``/``wait``, pipeline window > 1) is
    **bit-identical** to the blocking path (window 1) and to unbatched
    renders — pipelining must be invisible in the pixels;
  * completions are **out of dispatch order** under a straggler: a slow
    flight does not hold up the flights dispatched after it (pinned both
    in-process and over HTTP);
  * the engine's in-flight window is bounded, released on wait AND on
    abandon (a hung device must not wedge later submits);
  * the dispatch-gap metric reports device idle between flights (the
    blocking mode shows real gaps; the metric is how BENCH rounds prove
    the pipelined device never waits on the host).
"""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from mpi_vision_tpu.serve import (
    Fault,
    FaultyEngine,
    RenderEngine,
    RenderService,
    ResilienceConfig,
    make_http_server,
    synthetic_scene,
)
from mpi_vision_tpu.serve.cache import bake_scene

H = W = 16
P = 4


def _pose(tx=0.0, tz=0.0):
  pose = np.eye(4, dtype=np.float32)
  pose[0, 3], pose[2, 3] = tx, tz
  return pose


def _scene(sid="s", seed=0):
  return bake_scene(sid, *synthetic_scene(sid, H, W, P, seed=seed))


# --- engine streaming API ------------------------------------------------


def test_submit_poll_wait_matches_blocking():
  eng = RenderEngine(use_mesh=False, max_inflight=4)
  scene = _scene()
  poses = np.stack([_pose(0.01 * i) for i in range(3)])
  handle = eng.submit(scene, poses)
  deadline = time.monotonic() + 60
  while not eng.poll(handle) and time.monotonic() < deadline:
    time.sleep(0.002)
  out = eng.wait(handle)
  assert out.shape == (3, H, W, 3)
  assert handle.timings is not None
  assert set(handle.timings) == {"h2d_s", "compute_s", "readback_s"}
  np.testing.assert_array_equal(out, eng.render_batch(scene, poses))
  assert eng.inflight == 0  # every slot released


def test_engine_window_bounds_inflight_and_abandon_releases():
  eng = RenderEngine(use_mesh=False, max_inflight=1)
  scene = _scene()
  h1 = eng.submit(scene, _pose()[None])
  assert eng.inflight == 1
  submitted = threading.Event()

  def second():
    h = eng.submit(scene, _pose(0.01)[None])  # blocks until a slot frees
    submitted.set()
    eng.wait(h)

  t = threading.Thread(target=second, daemon=True)
  t.start()
  assert not submitted.wait(0.3)  # window of 1 really backpressures
  # Abandon frees the slot WITHOUT waiting on the result...
  h1.abandon()
  assert submitted.wait(30)
  t.join(30)
  assert eng.abandoned == 1
  # ...and the abandoned handle's late wait is still safe (idempotent
  # slot release, result intact).
  out = eng.wait(h1)
  assert out.shape == (1, H, W, 3)
  assert eng.inflight == 0


def test_overlapped_submits_are_bit_identical_to_solo():
  """Three batches in flight at once read back exactly what three
  back-to-back blocking renders produce — the streaming engine's parity
  contract."""
  eng = RenderEngine(use_mesh=False, max_inflight=4)
  scene = _scene()
  all_poses = [np.stack([_pose(0.01 * i, -0.005 * j) for i in range(2)])
               for j in range(3)]
  handles = [eng.submit(scene, p) for p in all_poses]
  outs = [eng.wait(h) for h in handles]
  for poses, out in zip(all_poses, outs):
    np.testing.assert_array_equal(out, eng.render_batch(scene, poses))


# --- service: pipelined vs blocking parity -------------------------------


def test_pipelined_service_matches_blocking_service_bitwise():
  pose_list = [_pose(0.01 * i, 0.002 * i) for i in range(5)]
  results = {}
  for label, window in (("pipelined", 4), ("blocking", 1)):
    svc = RenderService(max_batch=4, max_wait_ms=5.0, max_inflight=window,
                        use_mesh=False)
    svc.add_synthetic_scenes(1, height=H, width=W, planes=P)
    try:
      futs = [svc.render_async("scene_000", p) for p in pose_list]
      results[label] = [f.result(120) for f in futs]
    finally:
      svc.close()
  for a, b in zip(results["pipelined"], results["blocking"]):
    np.testing.assert_array_equal(a, b)


# --- out-of-order completion under a straggler ---------------------------


def _straggler_service(max_inflight=4):
  """A pipelined service over a FaultyEngine (no faults queued yet);
  max_batch=1 so each request is its own flight."""
  eng = FaultyEngine(RenderEngine(use_mesh=False, max_inflight=8))
  svc = RenderService(engine=eng, max_batch=1, max_wait_ms=0.0,
                      max_inflight=max_inflight, use_mesh=False,
                      resilience=ResilienceConfig(
                          max_retries=0, watchdog_s=60.0,
                          breaker_threshold=100))
  svc.add_synthetic_scenes(1, height=H, width=W, planes=P)
  svc.warmup()
  return svc, eng


def test_futures_complete_out_of_dispatch_order_under_straggler():
  svc, eng = _straggler_service()
  try:
    baseline = svc.render("scene_000", _pose(0.01))
    eng.inject(Fault("slow", seconds=1.5))  # the NEXT dispatch straggles
    slow = svc.render_async("scene_000", _pose(0.01))
    # Wait until the straggler is actually in flight (claimed by its
    # completion worker) so the fast one is provably dispatched AFTER.
    deadline = time.monotonic() + 30
    while svc.stats()["pipeline"]["inflight"] == 0 \
        and time.monotonic() < deadline:
      time.sleep(0.005)
    fast = svc.render_async("scene_000", _pose(0.02))
    out_fast = fast.result(30)
    assert not slow.done()  # the later dispatch completed FIRST
    out_slow = slow.result(30)
    np.testing.assert_array_equal(out_slow, baseline)
    assert out_fast.shape == (H, W, 3)
    assert svc.stats()["pipeline"]["out_of_order_completions"] >= 1
  finally:
    eng.release.set()
    svc.close()


def test_http_completions_out_of_dispatch_order_under_straggler():
  """The acceptance pin: two HTTP clients, the first request straggles,
  the second response arrives first."""
  svc, eng = _straggler_service()
  httpd = make_http_server(svc, port=0)
  threading.Thread(target=httpd.serve_forever, daemon=True).start()
  base = f"http://127.0.0.1:{httpd.server_address[1]}"
  completions = []
  lock = threading.Lock()

  def post(tag, tx):
    body = json.dumps({"scene_id": "scene_000",
                       "pose": _pose(tx).tolist()}).encode()
    req = urllib.request.Request(base + "/render", data=body)
    with urllib.request.urlopen(req, timeout=60) as resp:
      assert resp.status == 200
    with lock:
      completions.append(tag)

  try:
    eng.inject(Fault("slow", seconds=1.5))
    t_slow = threading.Thread(target=post, args=("slow", 0.01), daemon=True)
    t_slow.start()
    deadline = time.monotonic() + 30
    while svc.stats()["pipeline"]["inflight"] == 0 \
        and time.monotonic() < deadline:
      time.sleep(0.005)
    t_fast = threading.Thread(target=post, args=("fast", 0.02), daemon=True)
    t_fast.start()
    t_fast.join(30)
    t_slow.join(30)
    assert completions == ["fast", "slow"]
  finally:
    eng.release.set()
    httpd.shutdown()
    svc.close()


# --- dispatch-gap metric -------------------------------------------------


def test_blocking_mode_reports_dispatch_gaps():
  """With a window of 1, every launch after a completion finds the
  device idle — the gap metric must record it (the A/B baseline's
  signature; the pipelined arm's gaps collapse toward zero under
  saturation, proven per BENCH round by serve_load --ab)."""
  svc = RenderService(max_batch=2, max_wait_ms=0.0, max_inflight=1,
                      use_mesh=False)
  svc.add_synthetic_scenes(1, height=H, width=W, planes=P)
  try:
    for i in range(3):
      svc.render("scene_000", _pose(0.01 * i))
    gap = svc.stats()["pipeline"]["dispatch_gap"]
    assert gap["count"] >= 2
    assert gap["total_s"] > 0 and gap["max_ms"] > 0
  finally:
    svc.close()


def test_stats_pipeline_and_per_scene_blocks():
  svc = RenderService(max_batch=2, max_wait_ms=1.0, max_inflight=3,
                      use_mesh=False)
  svc.add_synthetic_scenes(2, height=H, width=W, planes=P)
  try:
    svc.render("scene_000", _pose(0.01))
    svc.render("scene_001", _pose(0.02))
    stats = svc.stats()
    assert json.loads(json.dumps(stats)) == stats  # JSON-clean
    pipe = stats["pipeline"]
    assert pipe["max_inflight"] == 3 and pipe["inflight"] == 0
    assert pipe["abandoned_batches"] == 0
    assert set(pipe["dispatch_gap"]) == {"count", "total_s", "mean_ms",
                                         "max_ms"}
    per_scene = stats["per_scene"]
    assert set(per_scene) == {"scene_000", "scene_001"}
    for entry in per_scene.values():
      assert entry["requests"] == 1
      assert entry["p50_ms"] > 0 and entry["max_ms"] >= entry["p50_ms"]
  finally:
    svc.close()


def test_abandoned_flight_is_counted_and_engine_slot_freed():
  """A flight whose every attempt trips the watchdog is abandoned: its
  futures fail, abandoned_batches increments, and the engine window is
  released so the NEXT request still dispatches."""
  eng = FaultyEngine(RenderEngine(use_mesh=False, max_inflight=8))
  svc = RenderService(engine=eng, max_batch=1, max_wait_ms=0.0,
                      max_inflight=2, use_mesh=False,
                      resilience=ResilienceConfig(
                          max_retries=0, watchdog_s=0.5,
                          breaker_threshold=100))
  svc.add_synthetic_scenes(1, height=H, width=W, planes=P)
  try:
    svc.warmup()
    eng.inject(Fault("hang", seconds=60.0))
    with pytest.raises(Exception, match="deadline|abandoned"):
      svc.render("scene_000", _pose(0.01), timeout=10.0)
    assert svc.stats()["pipeline"]["abandoned_batches"] == 1
    # The pipeline is still live: a clean request serves normally.
    out = svc.render("scene_000", _pose(0.01), timeout=30.0)
    assert out.shape == (H, W, 3)
  finally:
    eng.release.set()
    svc.close()


# --- adaptive in-flight window (--max-inflight auto) ---------------------


def test_adaptive_window_decision_logic():
  """The pure growth policy: probe upward first, keep growing while the
  mean device-idle gap per flight improves >= 5%, settle when it stops,
  when the device never idles, or at the cap."""
  from mpi_vision_tpu.serve.scheduler import MicroBatcher

  nw = MicroBatcher._next_window
  assert nw(None, 0.05, 2, 8, 0.05) == (3, False)   # first epoch: probe
  assert nw(0.05, 0.04, 3, 8, 0.05) == (4, False)   # improving: grow
  assert nw(0.04, 0.039, 4, 8, 0.05) == (4, True)   # <5% better: settle
  assert nw(0.04, 0.05, 4, 8, 0.05) == (4, True)    # worse: settle
  assert nw(0.04, 0.0, 4, 8, 0.05) == (4, True)     # never idle: settle
  assert nw(0.01, 0.001, 8, 8, 0.05) == (8, True)   # at cap: settle


def test_adaptive_service_grows_within_cap_and_serves():
  """``max_inflight="auto"``: the window starts at 2, every request
  still renders correctly, and after enough flights the window sits in
  [2, cap] with the adaptive block visible in /stats."""
  svc = RenderService(max_inflight="auto", max_inflight_cap=4,
                      max_batch=2, max_wait_ms=0.0, use_mesh=False)
  svc.add_synthetic_scenes(1, height=H, width=W, planes=P)
  try:
    # Drive enough flights (max_batch 2, serial submits => 1 per flight)
    # to cross at least one 32-flight adaptation epoch.
    svc.scheduler._adapt_every = 8
    reference = svc.render("scene_000", _pose(0.01))
    for _ in range(20):
      out = svc.render("scene_000", _pose(0.01))
    assert out.tobytes() == reference.tobytes()
    stats = svc.stats()
    adaptive = stats["pipeline"]["adaptive"]
    assert adaptive["cap"] == 4 and adaptive["epochs"] >= 1
    assert 2 <= stats["pipeline"]["max_inflight"] <= 4
    assert svc.scheduler.dispatcher_alive()
  finally:
    svc.close()


def test_adaptive_rejects_bad_knobs():
  with pytest.raises(ValueError, match="auto"):
    RenderService(max_inflight="fast")
  from mpi_vision_tpu.serve.scheduler import MicroBatcher

  with pytest.raises(ValueError, match="max_inflight_cap"):
    MicroBatcher(object(), lambda s: None, max_inflight=8,
                 max_inflight_cap=4)
