"""Session tier (PR 20): pose-in/frame-out streaming over POST /session.

Pins the contracts ISSUE 20 names: frames arrive in pose order and stay
bit-identical to the unbatched render path (fusion changes scheduling,
never pixels); a hostile pose stream — unknown kind, truncated payload,
oversize declared length, non-finite pose — closes THAT session cleanly
(in-stream error frame then end frame), never a 500 and never a dead
dispatcher (mirroring tests/serve/test_http_fuzz.py); opens past the
session bound shed with 503 + Retry-After; idle sessions are reaped on
the manager's injectable clock; brownout L3+ mutes the prefetch
predictor at the source; and the attribution ledger's conservation
invariant holds with session frames included.
"""

import http.client
import json
import socket
import struct
import threading

import numpy as np
import pytest

from mpi_vision_tpu.obs.attrib import AttribConfig
from mpi_vision_tpu.serve import RenderService, make_http_server
from mpi_vision_tpu.serve.metrics import ServeMetrics
from mpi_vision_tpu.serve.session import (
    SessionClient,
    SessionConfig,
    SessionManager,
    SessionOpenError,
    protocol,
)
from mpi_vision_tpu.serve.session.manager import SessionLimitError


@pytest.fixture(scope="module")
def served():
  # Edge cache off: every session frame is a real render, so the
  # bit-exactness pin compares like with like. Attribution on: session
  # frames must land in the ledger and keep conservation true.
  svc = RenderService(max_batch=4, max_wait_ms=0.5, resilience=None,
                      attrib=AttribConfig(),
                      session=SessionConfig(max_sessions=2, fuse_max=2,
                                            prefetch_horizon=0))
  svc.add_synthetic_scenes(1, height=16, width=16, planes=2)
  httpd = make_http_server(svc, port=0)
  thread = threading.Thread(target=httpd.serve_forever, daemon=True)
  thread.start()
  try:
    yield svc, httpd.server_address[1]
  finally:
    httpd.shutdown()
    svc.close()


def _poses(n):
  out = []
  for i in range(n):
    pose = np.eye(4, dtype=np.float32)
    pose[0, 3] = 0.05 * i
    pose[2, 3] = 2.0 + 0.02 * i
    out.append(pose)
  return out


def _post(port, body: bytes, path="/render"):
  conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
  try:
    conn.request("POST", path, body=body,
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    return resp.status, dict(resp.getheaders()), resp.read()
  finally:
    conn.close()


def _render_body():
  return json.dumps({"scene_id": "scene_000",
                     "pose": np.eye(4).tolist()}).encode()


def _drain_events(client):
  """Read server frames until end-of-stream/EOF; returns [(kind, parsed)]."""
  events = []
  while True:
    event = client.read_event()
    if event is None:
      return events
    events.append(event)
    if event[0] == protocol.KIND_END:
      return events


# -- happy path -----------------------------------------------------------


def test_session_streams_frames_in_pose_order(served):
  svc, port = served
  poses = _poses(5)
  with SessionClient("127.0.0.1", port, "scene_000") as client:
    assert client.session_id
    assert client.shape == (16, 16, 3)
    for pose in poses:
      client.send_pose(pose)
    client.end()
    frames = list(client.frames())
  assert [seq for seq, _ in frames] == list(range(len(poses)))
  for _, img in frames:
    assert img.shape == (16, 16, 3) and img.dtype == np.float32
    assert np.all(np.isfinite(img))
  assert svc.scheduler.dispatcher_alive()


def test_session_frames_bit_identical_to_unbatched_renders(served):
  """Fusion changes scheduling, never pixels (the ISSUE-20 parity pin)."""
  svc, port = served
  poses = _poses(4)
  with SessionClient("127.0.0.1", port, "scene_000") as client:
    for pose in poses:
      client.send_pose(pose)
    client.end()
    frames = dict(client.frames())
  assert len(frames) == len(poses)
  for seq, pose in enumerate(poses):
    solo = np.asarray(svc.render("scene_000", pose))
    np.testing.assert_array_equal(frames[seq], solo)


def test_stats_and_metrics_expose_the_session_block(served):
  svc, port = served
  with SessionClient("127.0.0.1", port, "scene_000") as client:
    client.send_pose(np.eye(4))
    client.end()
    assert len(list(client.frames())) == 1
  block = svc.stats()["session"]
  assert block["enabled"] is True
  assert block["max_sessions"] == 2 and block["fuse_max"] == 2
  assert block["opened"] >= 1 and block["closed"] >= 1
  assert block["frames"] >= 1 and block["frame_errors"] == 0
  assert block["flushes"] >= 1 and block["active"] == 0


def test_attrib_conservation_holds_with_session_frames(served):
  svc, port = served
  with SessionClient("127.0.0.1", port, "scene_000") as client:
    for pose in _poses(3):
      client.send_pose(pose)
    client.end()
    assert len(list(client.frames())) == 3
  attrib = svc.stats()["attrib"]
  assert attrib["conservation"]["ok"], attrib["conservation"]
  assert attrib["totals"]["requests"] >= 3


# -- hello validation -----------------------------------------------------


@pytest.mark.parametrize("body", [
    b"",                                         # empty -> KeyError
    b"not json at all",
    b"[1, 2, 3]",                                # not an object
    b"{\"scene_id\": 7}",                        # non-string scene id
    json.dumps({"scene_id": "scene_000\x1ft0,0"}).encode(),  # control char
], ids=["empty", "notjson", "array", "intid", "ctrlchar"])
def test_malformed_hello_is_400(served, body):
  svc, port = served
  status, headers, payload = _post(port, body, path="/session")
  assert status == 400, payload
  assert "error" in json.loads(payload)
  assert headers.get("X-Trace-Id")
  assert svc.scheduler.dispatcher_alive()


def test_unknown_scene_hello_is_404(served):
  svc, port = served
  status, _, payload = _post(port, json.dumps({"scene_id": "nope"}).encode(),
                             path="/session")
  assert status == 404, payload
  assert svc.scheduler.dispatcher_alive()


def test_sessions_disabled_is_503():
  # No session= -> POST /session refuses before touching scenes.
  svc = RenderService(max_batch=2, max_wait_ms=0.5, resilience=None)
  httpd = make_http_server(svc, port=0)
  threading.Thread(target=httpd.serve_forever, daemon=True).start()
  try:
    port = httpd.server_address[1]
    status, _, payload = _post(port, json.dumps({"scene_id": "x"}).encode(),
                               path="/session")
    assert status == 503
    assert "disabled" in json.loads(payload)["error"]
    with pytest.raises(SessionOpenError) as err:
      SessionClient("127.0.0.1", port, "x")
    assert err.value.status == 503
  finally:
    httpd.shutdown()
    svc.close()


# -- pose-stream fuzz -----------------------------------------------------

_FUZZ_STREAMS = [
    ("unknown_kind", struct.pack("<cI", b"Z", 0)),
    ("server_only_kind", struct.pack("<cI", b"F", 4) + b"\x00" * 4),
    ("oversize_length", struct.pack("<cI", b"P", 1 << 20)),
    ("short_pose", struct.pack("<cI", b"P", 10) + b"\x00" * 10),
    ("nonfinite_pose",
     struct.pack("<cI", b"P", protocol.POSE_BYTES)
     + np.full((4, 4), np.nan, dtype="<f4").tobytes()),
    ("truncated_payload", struct.pack("<cI", b"P", protocol.POSE_BYTES)
     + b"\x00" * 10),  # fewer bytes than declared, then write-side close
]


@pytest.mark.parametrize("raw", [r for _, r in _FUZZ_STREAMS],
                         ids=[n for n, _ in _FUZZ_STREAMS])
def test_hostile_pose_stream_closes_cleanly(served, raw):
  """Any framing garbage -> in-stream error frame then end frame; the
  session dies, the service doesn't."""
  svc, port = served
  with SessionClient("127.0.0.1", port, "scene_000") as client:
    client.send_pose(np.eye(4))  # a good pose first: its frame must land
    client.send_raw(raw)
    # EOF is the only way the server can detect a payload that never
    # finishes arriving; harmless for the other cases.
    client.sock.shutdown(socket.SHUT_WR)
    events = _drain_events(client)
  kinds = [kind for kind, _ in events]
  assert kinds, "server closed without an end frame"
  assert kinds[0] == protocol.KIND_FRAME  # the good pose rendered
  assert kinds[-1] == protocol.KIND_END
  assert protocol.KIND_ERROR in kinds
  error = next(parsed for kind, parsed in events
               if kind == protocol.KIND_ERROR)
  assert "bad pose stream" in error["error"]
  assert set(kinds) <= {protocol.KIND_FRAME, protocol.KIND_ERROR,
                        protocol.KIND_END}
  # The barrage cost one session, nothing else.
  assert svc.scheduler.dispatcher_alive()
  status, _, _ = _post(port, _render_body())
  assert status == 200
  assert svc.stats()["session"]["active"] == 0


def test_midstream_disconnect_does_not_kill_the_service(served):
  svc, port = served
  client = SessionClient("127.0.0.1", port, "scene_000")
  client.send_pose(np.eye(4))
  client.close()  # vanish without an end frame
  # The reaper path is exercised elsewhere; here the read loop sees EOF.
  status, _, _ = _post(port, _render_body())
  assert status == 200
  assert svc.scheduler.dispatcher_alive()


# -- session bound --------------------------------------------------------


def test_opens_past_the_bound_shed_503_with_retry_after(served):
  svc, port = served
  held = [SessionClient("127.0.0.1", port, "scene_000") for _ in range(2)]
  try:
    status, headers, payload = _post(
        port, json.dumps({"scene_id": "scene_000"}).encode(), path="/session")
    assert status == 503, payload
    assert int(headers["Retry-After"]) >= 1
    assert json.loads(payload)["retry_after_s"] == pytest.approx(1.0)
    with pytest.raises(SessionOpenError) as err:
      SessionClient("127.0.0.1", port, "scene_000")
    assert err.value.status == 503
  finally:
    for client in held:
      client.end()
      _drain_events(client)
      client.close()
  assert svc.stats()["session"]["rejected"] >= 2
  # The bound frees as sessions close: a new open succeeds.
  with SessionClient("127.0.0.1", port, "scene_000") as client:
    client.send_pose(np.eye(4))
    client.end()
    assert len(list(client.frames())) == 1


# -- manager units: idle reap on a fake clock, brownout prefetch mute -----


class _StubService:
  """The slice of RenderService the manager touches in these units."""

  def __init__(self):
    self.metrics = ServeMetrics()
    self.edge = None
    self.brownout = None

  def edge_cell_resident(self, scene_id, pose):
    return None, False  # no lattice -> nothing to prefetch into


def test_idle_sessions_reap_on_the_injected_clock():
  t = [100.0]
  svc = _StubService()
  mgr = SessionManager(SessionConfig(max_sessions=1, idle_timeout_s=5.0),
                       service=svc, clock=lambda: t[0])
  session = mgr.open("scene_000")
  assert mgr.active == 1
  with pytest.raises(SessionLimitError):
    mgr.open("scene_000")  # at the bound
  t[0] += 4.0
  assert mgr.reap_idle() == []  # inside the timeout: untouched
  t[0] += 2.0  # 6 s idle total > 5 s
  assert mgr.reap_idle() == [session.session_id]
  assert session.closed and session.close_reason == "idle"
  assert mgr.active == 0
  snap = svc.metrics.snapshot()["session"]
  assert snap["idle_reaped"] == 1 and snap["closed"] == 1
  # open() reaps before counting, so the freed slot admits the next open.
  t[0] += 100.0
  replacement = mgr.open("scene_000")
  assert mgr.active == 1
  replacement.close()


def test_brownout_l3_mutes_the_prefetch_predictor():
  svc = _StubService()
  svc.edge = object()  # non-None: prefetch would otherwise engage

  class _Brownout:
    level = 3

  svc.brownout = _Brownout()
  mgr = SessionManager(SessionConfig(prefetch_horizon=2), service=svc)
  session = mgr.open("scene_000")
  try:
    for pose in _poses(4):
      session._maybe_prefetch([pose])
    snap = svc.metrics.snapshot()["session"]["prefetch"]
    assert snap["issued"] == 0
    assert snap["suppressed"] == 4  # muted at the source every flush
    # Below L3 the ladder admits the class again: the predictor runs
    # (nothing resident to skip in the stub) and nothing is suppressed.
    svc.brownout.level = 2
    for pose in _poses(4):
      session._maybe_prefetch([pose])
    snap = svc.metrics.snapshot()["session"]["prefetch"]
    assert snap["suppressed"] == 4
  finally:
    session.close()
    mgr.close_all()
