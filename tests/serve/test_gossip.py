"""Router HA: gossip merge, supervision leases, leased handoff.

Three layers, cheapest first:

  * ``GossipState``/``GossipNode`` units on fake clocks and transports —
    newest-version-wins merge, conflict counting with the deterministic
    origin tie-break, the lease slot's fresh-beats-stale rules, and
    push-pull convergence of two partitioned peers in one round.
  * ``FileLease``/``GossipLease`` state machines — atomic claim,
    heartbeat, stale-holder reap (takeover), split-brain heal, and the
    ``SupervisionLeaseLost`` demotion the loser must obey.
  * Leased ``FleetSupervisor`` handoff over fakes + ONE real-process
    failover arc: supervisor A spends restart budget and quarantines a
    backend, publishes observations into gossip, dies (stops
    heartbeating); supervisor B reaps the stale lease, adopts the
    gossiped budget/quarantine state, and the crash-looper CANNOT reset
    its countdown by outliving its supervisor — the acceptance pin of
    the router-HA tier.
"""

import json
import os
import sys
import time

import numpy as np
import pytest

from mpi_vision_tpu.serve.cluster import (
    BackendPool,
    FileLease,
    FleetSupervisor,
    GossipLease,
    GossipNode,
    GossipState,
    RemoteBackendPool,
    Router,
    SupervisionLeaseLost,
)
from mpi_vision_tpu.serve.cluster.pool import BackendSpawnError

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


class FakeClock:
  def __init__(self, t=1000.0):
    self.t = t

  def __call__(self):
    return self.t


# --- GossipState: versioned observations ---------------------------------


def test_gossip_observe_bumps_version_only_on_change():
  clock = FakeClock()
  state = GossipState("routerA", clock=clock)
  assert state.observe("b0", state="up", quarantined=False)
  v1 = state.observation("b0")["version"]
  assert not state.observe("b0", state="up")  # no-op: nothing changed
  assert state.observation("b0")["version"] == v1
  clock.t += 1.0
  assert state.observe("b0", state="down")
  obs = state.observation("b0")
  assert obs["version"] > v1 and obs["origin"] == "routerA"
  # Fields MERGE over the previous observation (partial updates keep
  # the rest of the record).
  assert obs["fields"] == {"state": "down", "quarantined": False}


def test_gossip_merge_newest_version_wins_and_wire_roundtrips():
  clock = FakeClock()
  a = GossipState("routerA", clock=clock)
  b = GossipState("routerB", clock=clock)
  a.observe("b0", state="up")
  clock.t += 1.0
  b.observe("b0", state="down")  # newer observation of the same backend
  # The wire form is JSON-safe both ways (it crosses /gossip).
  wire = json.loads(json.dumps(b.wire()))
  result = a.merge(wire)
  assert result["merges"] == 1 and result["conflicts"] == 0
  assert result["changed"] == ["b0"]
  assert a.observation("b0")["fields"]["state"] == "down"
  # The older state flowing back the other way is NOT adopted.
  stale = json.loads(json.dumps(a.wire()))
  stale["observations"]["b0"]["version"] -= 2.0
  stale["observations"]["b0"]["fields"] = {"state": "up"}
  result = b.merge(stale)
  assert result["merges"] == 0
  assert b.observation("b0")["fields"]["state"] == "down"


def test_gossip_merge_version_tie_counts_conflict_and_both_sides_agree():
  clock = FakeClock()
  a = GossipState("routerA", clock=clock)
  b = GossipState("routerB", clock=clock)
  # Same version, different fields, different origins: the partitioned
  # split-brain worst case. Both sides must converge to ONE winner.
  entry_a = {"version": 5.0, "origin": "routerA", "fields": {"x": 1}}
  entry_b = {"version": 5.0, "origin": "routerB", "fields": {"x": 2}}
  a.merge({"observations": {"b0": entry_a}})
  b.merge({"observations": {"b0": entry_b}})
  ra = a.merge({"observations": {"b0": entry_b}})
  rb = b.merge({"observations": {"b0": entry_a}})
  assert ra["conflicts"] == 1 and rb["conflicts"] == 1
  # Greater origin id wins deterministically on BOTH sides.
  assert a.observation("b0")["fields"] == {"x": 2}
  assert b.observation("b0")["fields"] == {"x": 2}


def test_gossip_merge_malformed_entries_never_poison_the_table():
  state = GossipState("routerA", clock=FakeClock())
  state.observe("b0", state="up")
  result = state.merge({"observations": {
      "b0": {"version": "not-a-number", "origin": "x", "fields": {}},
      "b1": {"origin": "x", "fields": {}},           # missing version
      "b2": "garbage",                               # not even a dict
  }, "lease": "garbage"})
  assert result["merges"] == 0 and result["conflicts"] == 0
  assert state.observation("b0")["fields"] == {"state": "up"}
  assert state.observation("b1") is None


# --- GossipState: the lease slot -----------------------------------------


def test_gossip_lease_merge_same_owner_newer_heartbeat_wins():
  clock = FakeClock()
  a = GossipState("routerA", clock=clock)
  a.claim_lease("routerA")
  newer = dict(a.lease_view())
  newer["heartbeat_unix_s"] += 2.0
  b = GossipState("routerB", clock=clock)
  b.merge({"lease": newer})
  # The older heartbeat flowing in afterwards does not roll it back.
  b.merge({"lease": a.claim_lease("routerA")})
  assert b.lease_view()["heartbeat_unix_s"] == newer["heartbeat_unix_s"]


def test_gossip_lease_merge_fresh_beats_stale_and_ties_break_earliest():
  clock = FakeClock()
  a = GossipState("routerA", clock=clock, lease_ttl_s=5.0)
  b = GossipState("routerB", clock=clock, lease_ttl_s=5.0)
  a.claim_lease("routerA")
  clock.t += 1.0
  b.claim_lease("routerB")  # later claimant: split brain
  # Both fresh -> conflict, broken to the EARLIEST (since, owner) on
  # both sides: routerA claimed first and keeps the lease everywhere.
  rb = b.merge({"lease": a.lease_view()})
  ra = a.merge({"lease": b.lease_view()})
  assert rb["conflicts"] == 1 and ra["conflicts"] == 0
  assert a.lease_view()["owner"] == "routerA"
  assert b.lease_view()["owner"] == "routerA"
  # routerA goes quiet; once its heartbeat is stale a fresh claim wins.
  clock.t += 6.0
  b.claim_lease("routerB")
  a.merge({"lease": b.lease_view()})
  assert a.lease_view()["owner"] == "routerB" and a.lease_view()["fresh"]


# --- GossipNode: push-pull rounds ----------------------------------------


class NodeTransport:
  """peer address -> GossipNode; a round's POST becomes receive()."""

  def __init__(self):
    self.nodes = {}

  def request(self, method, url, body=None, headers=None, timeout=30.0):
    address, _, path = url[len("http://"):].partition("/")
    node = self.nodes.get(address)
    if node is None:
      raise ConnectionError("peer down")
    reply = node.receive(json.loads(body))
    return 200, {}, json.dumps(reply).encode()


def test_gossip_round_converges_partitioned_peers_and_counts_failures():
  clock = FakeClock()
  transport = NodeTransport()
  state_a = GossipState("routerA", clock=clock)
  state_b = GossipState("routerB", clock=clock)
  # Divergent histories from a partition: disjoint AND conflicting keys.
  state_a.observe("b0", state="up")
  state_a.observe("b1", state="down")
  clock.t += 1.0
  state_b.observe("b1", state="up")  # newer verdict on the shared key
  state_b.observe("b2", state="up")
  merged_on_b = []
  node_a = GossipNode(state_a, peers=["peer-b:1"], transport=transport,
                      clock=clock, sleep=lambda s: None)
  node_b = GossipNode(state_b, peers=["peer-a:1"], transport=transport,
                      clock=clock, sleep=lambda s: None,
                      on_merge=lambda ids: merged_on_b.append(ids))
  transport.nodes["peer-a:1"] = node_a
  transport.nodes["peer-b:1"] = node_b
  # ONE push-pull round converges both directions: A pushes its state
  # into B and merges B's reply.
  results = node_a.round()
  assert results == {"peer-b:1": "ok"}
  assert state_a.observations() == state_b.observations()
  assert state_a.observation("b1")["fields"]["state"] == "up"
  assert merged_on_b == [["b0"]]  # B adopted only A's novel key —
  # its own newer b1 verdict survived the push (newest wins).
  # A dead peer is counted and reported, never fatal.
  del transport.nodes["peer-b:1"]
  results = node_a.round()
  assert "ConnectionError" in results["peer-b:1"]
  peers = node_a.snapshot()["peers"]["peer-b:1"]
  assert peers["ok"] is False and peers["failures"] == 1
  assert node_a.rounds == 2


# --- FileLease -----------------------------------------------------------


def test_file_lease_acquire_heartbeat_release(tmp_path):
  clock = FakeClock()
  path = str(tmp_path / "sup.lease")
  a = FileLease(path, "routerA", ttl_s=5.0, clock=clock)
  b = FileLease(path, "routerB", ttl_s=5.0, clock=clock)
  got = a.try_acquire()
  assert got == {"takeover": False, "previous": None}
  assert b.try_acquire() is None  # held fresh by A
  assert b.holder()["owner"] == "routerA" and b.holder()["fresh"]
  clock.t += 3.0
  a.heartbeat()  # keeps the lease alive past the original stamp
  clock.t += 3.0  # 6s since acquire but only 3 since the heartbeat
  assert b.try_acquire() is None
  # Re-acquiring while held is an idempotent heartbeat, not a takeover.
  assert a.try_acquire() == {"takeover": False, "previous": "routerA"}
  a.release()
  got = b.try_acquire()
  assert got == {"takeover": False, "previous": None}  # clean handoff


def test_file_lease_stale_holder_is_reaped_as_takeover(tmp_path):
  clock = FakeClock()
  path = str(tmp_path / "sup.lease")
  a = FileLease(path, "routerA", ttl_s=2.0, clock=clock)
  b = FileLease(path, "routerB", ttl_s=2.0, clock=clock)
  a.try_acquire()
  clock.t += 2.5  # A died (no heartbeat): its lease goes stale
  assert b.holder()["fresh"] is False
  got = b.try_acquire()
  assert got == {"takeover": True, "previous": "routerA"}
  # The dead holder coming back finds its lease gone and steps down.
  with pytest.raises(SupervisionLeaseLost):
    a.heartbeat()


def test_gossip_lease_split_brain_heals_and_loser_steps_down():
  clock = FakeClock()
  state_a = GossipState("routerA", clock=clock, lease_ttl_s=5.0)
  state_b = GossipState("routerB", clock=clock, lease_ttl_s=5.0)
  lease_a = GossipLease(state_a, "routerA")
  lease_b = GossipLease(state_b, "routerB")
  # Partitioned: both acquire optimistically (nobody can stop them).
  assert lease_a.try_acquire() is not None
  clock.t += 1.0
  assert lease_b.try_acquire() is not None
  # The partition heals at the first merge: earliest claimant wins in
  # BOTH states, and the loser's next heartbeat steps down.
  state_b.merge({"lease": state_a.lease_view()})
  state_a.merge({"lease": state_b.lease_view()})
  assert state_a.lease_view()["owner"] == "routerA"
  lease_a.heartbeat()
  with pytest.raises(SupervisionLeaseLost):
    lease_b.heartbeat()
  assert lease_b.try_acquire() is None  # and cannot reclaim while fresh
  # A releases cleanly in ITS state; B still sees the old claim until
  # it goes stale (a gossiped release is just a stopped heartbeat), so
  # B reclaims only after the TTL — marked as a takeover.
  lease_a.release()
  assert state_a.lease_view() is None
  clock.t += 6.0
  got = lease_b.try_acquire()
  assert got == {"takeover": True, "previous": "routerA"}


# --- leased FleetSupervisor handoff over fakes ---------------------------


class FakePool:
  def __init__(self, backends=("b0", "b1", "b2")):
    self.addrs = {b: f"host-{b}:1" for b in backends}
    self._alive = {b: True for b in backends}
    self.restarts: list[str] = []

  def addresses(self):
    return dict(self.addrs)

  def alive(self, backend_id):
    return self._alive[backend_id]

  def kill(self, backend_id, sig=None):
    self._alive[backend_id] = False

  def restart(self, backend_id):
    self.restarts.append(backend_id)
    self._alive[backend_id] = True
    return self.addrs[backend_id]

  def die(self, backend_id):
    self._alive[backend_id] = False


class FakeTransport:
  def __init__(self):
    self.handlers = {}

  def set_health(self, address, status):
    def handler(method, path):
      if path == "/healthz":
        return 200, {}, json.dumps({"status": status}).encode()
      if path == "/stats":
        return 200, {}, json.dumps({"queue_depth": 0}).encode()
      return 404, {}, b"{}"
    self.handlers[address] = handler

  def request(self, method, url, body=None, headers=None, timeout=30.0):
    address, _, path = url[len("http://"):].partition("/")
    return self.handlers[address]("GET", "/" + path)


def _leased_fleet(lease, gossip, clock, **sup_kwargs):
  """One router replica's worth of fakes: pool + router + supervisor
  holding (or standing by for) the shared supervision lease."""
  pool = FakePool()
  transport = FakeTransport()
  for addr in pool.addrs.values():
    transport.set_health(addr, "ok")
  router = Router(pool.addrs, replication=2, transport=transport,
                  clock=clock)
  sup = FleetSupervisor(
      pool, router=router, events=router.events, transport=transport,
      clock=clock, sleep=lambda s: None, load_refresh_s=0,
      lease=lease, gossip=gossip, **sup_kwargs)
  return pool, router, sup


def test_supervisor_standby_replica_neither_probes_nor_restarts(tmp_path):
  clock = FakeClock()
  path = str(tmp_path / "sup.lease")
  state_a = GossipState("routerA", clock=clock)
  state_b = GossipState("routerB", clock=clock)
  pool_a, router_a, sup_a = _leased_fleet(
      FileLease(path, "routerA", ttl_s=5.0, clock=clock), state_a, clock)
  pool_b, router_b, sup_b = _leased_fleet(
      FileLease(path, "routerB", ttl_s=5.0, clock=clock), state_b, clock)
  sup_a.tick()  # A wins the lease
  assert sup_a.snapshot()["lease_held"] is True
  assert router_a.metrics.snapshot()["supervisor_lease_held"] == 1
  pool_b.die("b1")  # B's view of the fleet degrades...
  sup_b.tick()
  # ...but B is standby: no probes spent, no restart attempted — the
  # leader owns the fleet and B only keeps trying for the lease.
  assert sup_b.snapshot()["lease_held"] is False
  assert sup_b.snapshot()["takeovers"] == 0
  assert pool_b.restarts == []
  assert router_b.metrics.snapshot()["supervisor_lease_held"] == 0
  # A holds through heartbeats; B stays standby as long as A is fresh.
  for _ in range(3):
    clock.t += 1.0
    sup_a.tick()
    sup_b.tick()
  assert sup_b.snapshot()["lease_held"] is False


def test_supervisor_takeover_adopts_gossiped_budget_no_reset(tmp_path):
  """THE handoff pin: budget spends survive the supervisor's death.

  A spends its full restart budget on a crash-looper, publishes the
  spends into gossip, and dies. B reaps the stale lease, adopts the
  gossiped ages, and the looper's NEXT failure quarantines immediately
  — zero fresh restarts granted by the handoff."""
  clock = FakeClock()
  path = str(tmp_path / "sup.lease")
  state_a = GossipState("routerA", clock=clock)
  state_b = GossipState("routerB", clock=clock)
  pool_a, router_a, sup_a = _leased_fleet(
      FileLease(path, "routerA", ttl_s=5.0, clock=clock), state_a, clock,
      restart_budget=2, budget_window_s=1000.0, backoff_base_s=0.1,
      backoff_max_s=0.1)
  pool_b, router_b, sup_b = _leased_fleet(
      FileLease(path, "routerB", ttl_s=5.0, clock=clock), state_b, clock,
      restart_budget=2, budget_window_s=1000.0, backoff_base_s=0.1,
      backoff_max_s=0.1)
  # A supervises and burns the whole budget on b1's crash loop.
  sup_a.tick()
  pool_a.die("b1")
  sup_a.tick()  # restart 1 (immediate: first of the episode)
  pool_a.die("b1")
  clock.t += 0.2
  sup_a.tick()  # detection; 0.1s backoff
  clock.t += 0.2
  sup_a.tick()  # restart 2: budget now exhausted
  assert pool_a.restarts == ["b1", "b1"]
  # The tick published the spends as ages; anti-entropy carries them.
  ages = state_a.observation("b1")["fields"]["budget_ages_s"]
  assert len(ages) == 2
  state_b.merge(state_a.wire())
  # A dies (no release, no heartbeat). Its lease goes stale...
  clock.t += 6.0
  sup_b.tick()
  # ...and B takes over, adopting the budget instead of resetting it.
  snap_b = sup_b.snapshot()
  assert snap_b["lease_held"] is True and snap_b["takeovers"] == 1
  assert router_b.metrics.snapshot()["supervisor_takeovers"] == 1
  assert snap_b["backends"]["b1"]["budget"]["in_window"] == 2
  # The looper dies once more under B: quarantined IMMEDIATELY — the
  # handoff granted it zero fresh restarts.
  pool_b.die("b1")
  sup_b.tick()
  assert sup_b.state("b1") == FleetSupervisor.QUARANTINED
  assert pool_b.restarts == []
  assert router_b.ejected() == ["b1"]
  # The dead leader coming back mid-tick demotes itself to standby.
  sup_a.tick()
  assert sup_a.snapshot()["lease_held"] is False
  assert router_a.events.count("supervision_lease_lost") == 1
  assert router_b.events.count("supervision_takeover") == 1


def test_supervisor_takeover_adopts_gossiped_quarantine(tmp_path):
  """A quarantine verdict survives the handoff: the new leader keeps
  the backend out of rotation without re-litigating the crash loop."""
  clock = FakeClock()
  path = str(tmp_path / "sup.lease")
  state_a = GossipState("routerA", clock=clock)
  state_b = GossipState("routerB", clock=clock)
  pool_a, router_a, sup_a = _leased_fleet(
      FileLease(path, "routerA", ttl_s=5.0, clock=clock), state_a, clock,
      restart_budget=1, budget_window_s=1000.0, backoff_base_s=0.1,
      backoff_max_s=0.1)
  pool_b, router_b, sup_b = _leased_fleet(
      FileLease(path, "routerB", ttl_s=5.0, clock=clock), state_b, clock,
      restart_budget=1, budget_window_s=1000.0, backoff_base_s=0.1,
      backoff_max_s=0.1)
  sup_a.tick()
  pool_a.die("b2")
  sup_a.tick()  # restart 1: budget spent
  pool_a.die("b2")
  clock.t += 0.2
  sup_a.tick()
  clock.t += 0.2
  sup_a.tick()  # budget refused -> quarantined
  assert sup_a.state("b2") == FleetSupervisor.QUARANTINED
  assert state_a.observation("b2")["fields"]["quarantined"] is True
  state_b.merge(state_a.wire())
  clock.t += 6.0
  sup_b.tick()  # takeover adopts the verdict BEFORE the first probe
  assert sup_b.state("b2") == FleetSupervisor.QUARANTINED
  assert "b2" in router_b.ejected()
  assert router_b.stats()["backend_info"]["b2"]["eject_reason"] \
      == "quarantined"
  # Sticky under the new leader too: no respawns ever granted.
  for _ in range(3):
    clock.t += 1.0
    sup_b.tick()
  assert pool_b.restarts == []


# --- RemoteBackendPool: supervising a joined fleet -----------------------


def test_remote_pool_runs_hook_with_backend_argv():
  calls = []

  def runner(argv, timeout=None, capture_output=None):
    calls.append((argv, timeout))

    class R:
      returncode = 0
    return R()

  pool = RemoteBackendPool({"b0": "10.0.0.1:7070"},
                           restart_hook="notify-owner --urgency high",
                           hook_timeout_s=7.0, runner=runner)
  assert pool.alive("b0")  # liveness is the prober's judgment
  pool.kill("b0")          # no local process: a no-op, never an error
  assert pool.alive("b0")
  address = pool.restart("b0")
  assert address == "10.0.0.1:7070"
  # shlex argv + [backend_id, address] — the k8s-operator webhook shape.
  assert calls == [(["notify-owner", "--urgency", "high", "b0",
                     "10.0.0.1:7070"], 7.0)]
  assert pool.snapshot()["hook_invocations"] == 1
  assert pool.snapshot()["hook_failures"] == 0


def test_remote_pool_hook_failures_raise_and_count():
  def failing_runner(argv, timeout=None, capture_output=None):
    class R:
      returncode = 3
    return R()

  pool = RemoteBackendPool({"b0": "10.0.0.1:7070"},
                           restart_hook="broken-hook",
                           runner=failing_runner)
  with pytest.raises(BackendSpawnError):
    pool.restart("b0")

  def crashing_runner(argv, timeout=None, capture_output=None):
    raise OSError("no such file")

  pool._runner = crashing_runner
  with pytest.raises(BackendSpawnError):
    pool.restart("b0")
  assert pool.hook_failures == 2 and pool.hook_invocations == 2
  with pytest.raises(KeyError):
    pool.restart("nope")


def test_remote_pool_hook_failure_is_counted_by_supervisor_never_fatal():
  """A broken webhook must not kill supervision: the supervisor counts
  the failed 'spawn', keeps probing, and quarantines at the budget."""
  def failing_runner(argv, timeout=None, capture_output=None):
    class R:
      returncode = 1
    return R()

  clock = FakeClock()
  pool = RemoteBackendPool({"b0": "10.0.0.9:7070"},
                           restart_hook="broken-hook",
                           runner=failing_runner)
  transport = FakeTransport()
  # The remote backend is unreachable: no handler -> ConnectionError.
  transport.handlers["10.0.0.9:7070"] = \
      lambda method, path: (_ for _ in ()).throw(
          ConnectionError("refused"))
  sup = FleetSupervisor(pool, transport=transport, clock=clock,
                        sleep=lambda s: None, load_refresh_s=0,
                        wedge_after=1, restart_budget=2,
                        budget_window_s=1000.0, backoff_base_s=0.1,
                        backoff_max_s=0.1)
  for _ in range(8):
    sup.tick()
    clock.t += 0.2
  snap = sup.snapshot()["backends"]["b0"]
  assert snap["restart_failures"] >= 1  # counted...
  assert sup.snapshot()["tick_errors"] == 0  # ...never fatal
  assert sup.state("b0") == FleetSupervisor.QUARANTINED
  assert pool.hook_failures == snap["restart_failures"]


# --- the real thing: leased handoff over a live fleet --------------------


@pytest.fixture(scope="module")
def live_fleet(healed_backends):
  """The session-shared backend pool (conftest.backend_pool), re-gated
  healthy — the lease-handoff arc needs real processes to kill and
  respawn, not a particular pool size."""
  return healed_backends


def _render_body(sid):
  return json.dumps({"scene_id": sid,
                     "pose": np.eye(4).tolist()}).encode()


def test_live_failover_arc_lease_handoff_and_respawn(live_fleet, tmp_path):
  """The real-process failover arc: two router replicas supervise one
  LIVE fleet through a shared FileLease. The leader restarts a killed
  backend and publishes the spend into gossip; then the leader dies
  (stops heartbeating), the standby reaps the stale lease mid-stream,
  adopts the budget, and a backend killed AFTER the takeover is
  respawned by the NEW leader — requests succeed throughout."""
  pool, backends = live_fleet
  path = str(tmp_path / "sup.lease")
  state_a = GossipState("routerA", lease_ttl_s=1.0)
  state_b = GossipState("routerB", lease_ttl_s=1.0)

  def replica(node_id, state):
    router = Router(backends, replication=2, breaker_threshold=2,
                    breaker_reset_s=0.3, render_timeout_s=120.0)
    sup = FleetSupervisor(
        pool, router=router, events=router.events,
        probe_s=0.05, backoff_base_s=0.05, backoff_max_s=0.2,
        load_refresh_s=0, restart_budget=5, budget_window_s=300.0,
        lease=FileLease(path, node_id, ttl_s=1.0),
        gossip=state, log=lambda m: print(m, file=sys.stderr))
    return router, sup

  router_a, sup_a = replica("routerA", state_a)
  router_b, sup_b = replica("routerB", state_b)
  sids = pool.scene_ids()
  # The victim must be a backend that actually serves sids[0]: the
  # phase-4 convergence check waits for IT to answer that scene, and
  # on a >2-backend pool an arbitrary backend may not be in placement.
  victim = router_a.placement(sids[0])[0]

  # Phase 1: A leads, B stands by; the fleet serves through BOTH
  # router replicas (routing never needed the lease).
  sup_a.tick()
  sup_b.tick()
  assert sup_a.snapshot()["lease_held"] is True
  assert sup_b.snapshot()["lease_held"] is False
  for router in (router_a, router_b):
    status, _, _ = router.forward_render(sids[0], _render_body(sids[0]))
    assert status == 200

  # Phase 2: a backend dies under the leader; one tick respawns it and
  # the spend lands in gossip (anti-entropy simulated by one merge —
  # in production GossipNode rounds carry it).
  pool.kill(victim)
  sup_a.tick()
  assert pool.alive(victim)
  assert state_a.observation(victim)["fields"]["budget_ages_s"]
  state_b.merge(state_a.wire())

  # Phase 3: the leader dies mid-stream (no release — a SIGKILL'd
  # router heartbeats never again). The standby reaps the stale lease.
  time.sleep(1.3)  # > ttl_s: the lease is now stale on disk
  sup_b.tick()
  snap_b = sup_b.snapshot()
  assert snap_b["lease_held"] is True and snap_b["takeovers"] == 1
  assert snap_b["backends"][victim]["budget"]["in_window"] >= 1
  with pytest.raises(SupervisionLeaseLost):
    sup_a.lease.heartbeat()  # the corpse cannot sneak back in

  # Phase 4: a backend killed AFTER the takeover is respawned by the
  # NEW leader — supervision truly moved, and the fleet still serves.
  pool.kill(victim)
  deadline = time.monotonic() + 30.0
  while not pool.alive(victim) and time.monotonic() < deadline:
    sup_b.tick()
    time.sleep(0.05)
  assert pool.alive(victim), "new leader never respawned the backend"
  assert sup_b.snapshot()["backends"][victim]["restarts"] >= 1
  deadline = time.monotonic() + 30.0
  served = False
  while time.monotonic() < deadline:
    status, headers, _ = router_b.forward_render(
        sids[0], _render_body(sids[0]))
    assert status == 200
    if headers["X-Backend-Id"] == victim:
      served = True
      break
    time.sleep(0.05)
  assert served, "respawned backend never served under the new leader"
  assert router_b.metrics.snapshot()["supervisor_takeovers"] == 1
