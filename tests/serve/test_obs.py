"""Observability layer tests: tracer, Prometheus exposition, profiling.

Three acceptance pins live here: (1) every ``/render`` response carries
``X-Trace-Id`` and its span tree covers queue-wait, batch-assembly,
dispatch (with retry attempts as children), and readback; (2)
``/metrics`` parses with a minimal text-format parser, metric
names/types are pinned, and counter values agree with the ``/stats``
snapshot after a deterministic in-process load; (3) a failed cache bake
still produces a complete span tree with the error on the bake span.
"""

import contextlib
import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from mpi_vision_tpu.obs import (
    DeviceProfiler,
    ExpositionCache,
    ProfileBusyError,
    aggregate_metrics_texts,
    parse_metrics_text,
    render_serve_metrics,
)
from mpi_vision_tpu.obs.trace import NULL_TRACE, SpanRecorder, Tracer
from mpi_vision_tpu.serve import (
    Fault,
    FaultyEngine,
    RenderService,
    ResilienceConfig,
    make_http_server,
)
from mpi_vision_tpu.serve.engine import RenderEngine
from mpi_vision_tpu.serve.metrics import LATENCY_BUCKETS_S, ServeMetrics

H = W = 16
P = 4


class FakeClock:
  def __init__(self, t=0.0):
    self.t = t

  def __call__(self):
    return self.t

  def advance(self, dt):
    self.t += dt
    return self.t


def _pose(tx=0.0):
  pose = np.eye(4, dtype=np.float32)
  pose[0, 3] = tx
  return pose


# --- tracer --------------------------------------------------------------


def test_trace_span_tree_parents_and_relative_times():
  clock = FakeClock()
  tracer = Tracer(clock=clock)
  tr = tracer.start_trace("render", scene_id="s0")
  q = tr.start_span("queue_wait")
  clock.advance(0.010)
  tr.end_span(q)
  d = tr.add_span("dispatch", 0.010, 0.030)
  tr.add_span("attempt", 0.010, 0.030, parent=d, attempt=0)
  clock.advance(0.020)
  tr.finish()
  assert len(tr.trace_id) == 16
  out = tr.to_dict()
  assert out["duration_ms"] == pytest.approx(30.0)
  by_name = {s["name"]: s for s in out["spans"]}
  assert by_name["queue_wait"]["t0_ms"] == pytest.approx(0.0)
  assert by_name["queue_wait"]["duration_ms"] == pytest.approx(10.0)
  assert by_name["attempt"]["parent"] == by_name["dispatch"]["id"]
  assert by_name["attempt"]["attrs"]["attempt"] == 0
  assert json.loads(json.dumps(out)) == out  # JSON-clean


def test_tracer_disabled_is_the_noop_singleton():
  tracer = Tracer(enabled=False)
  tr = tracer.start_trace("render")
  assert tr is NULL_TRACE and tr.trace_id == ""
  assert tr.start_span("x") == 0
  tr.end_span(0)
  tr.finish()
  snap = tracer.snapshot()
  assert snap["started"] == 0 and snap["finished"] == 0
  assert snap["recent"] == [] and snap["slowest"] == []


def test_trace_finish_is_idempotent_first_wins():
  clock = FakeClock()
  tracer = Tracer(clock=clock)
  tr = tracer.start_trace("render")
  clock.advance(1.0)
  tr.finish(error="first")
  clock.advance(9.0)
  tr.finish()  # the late dispatcher resolution must not re-open it
  assert tracer.finished == 1
  rec = tracer.snapshot()["recent"][0]
  assert rec["error"] == "first"
  assert rec["duration_ms"] == pytest.approx(1000.0)


def test_tracer_ring_bounded_and_slowest_retained_past_eviction():
  clock = FakeClock()
  tracer = Tracer(clock=clock, ring=4, slow_keep=2)
  durations = [0.01, 0.5, 0.02, 0.03, 0.9, 0.04, 0.05, 0.06]
  for i, dur in enumerate(durations):
    tr = tracer.start_trace("render", idx=i)
    clock.advance(dur)
    tr.finish()
  snap = tracer.snapshot()
  assert len(snap["recent"]) == 4  # ring bound
  recent_ids = {t["attrs"]["idx"] for t in snap["recent"]}
  assert recent_ids == {4, 5, 6, 7}
  # The two slowest (0.9s and 0.5s) survive; 0.5s was evicted from the
  # ring long ago — exemplar retention is the point.
  slow_ms = [t["duration_ms"] for t in snap["slowest"]]
  assert slow_ms == [pytest.approx(900.0), pytest.approx(500.0)]


def test_tracer_emit_structured_json_lines():
  lines = []
  clock = FakeClock()
  tracer = Tracer(clock=clock, emit=lines.append)
  tr = tracer.start_trace("render", scene_id="s0")
  s = tr.start_span("queue_wait")
  clock.advance(0.25)
  tr.end_span(s)
  tr.finish()
  assert len(lines) == 1
  rec = json.loads(lines[0])
  assert rec["event"] == "trace" and rec["trace_id"] == tr.trace_id
  assert rec["spans"][0]["name"] == "queue_wait"


def test_tracer_emit_failure_never_propagates_to_finish():
  """finish() runs on the scheduler's only dispatcher thread: a dying
  emit sink (closed stderr pipe) must drop lines, not kill the thread."""
  def bad_emit(line):
    raise BrokenPipeError("log consumer went away")

  clock = FakeClock()
  tracer = Tracer(clock=clock, emit=bad_emit)
  tr = tracer.start_trace("render")
  clock.advance(0.01)
  tr.finish()  # must not raise
  snap = tracer.snapshot()
  assert snap["finished"] == 1 and snap["emit_errors"] == 1
  assert len(snap["recent"]) == 1  # the trace itself is still recorded


def test_tracer_snapshot_recent_zero_returns_none():
  clock = FakeClock()
  tracer = Tracer(clock=clock)
  for _ in range(3):
    tracer.start_trace("render").finish()
  snap = tracer.snapshot(recent=0)
  assert snap["recent"] == [] and snap["finished"] == 3
  assert len(tracer.snapshot(recent=2)["recent"]) == 2


def test_span_recorder_zombie_attempt_parents_to_its_own_group():
  """An attempt thread abandoned by the watchdog records with the parent
  captured at ITS entry — late spans land under the dead attempt, never
  under whichever attempt is live when they arrive."""
  clock = FakeClock()
  rec = SpanRecorder(clock)
  a0 = rec.begin("attempt", attempt=0)
  zombie_parent = rec.current_parent()  # what _span_render captures
  rec.end(a0, error="watchdog abandoned")
  a1 = rec.begin("attempt", attempt=1)
  # The zombie finishes now, while attempt 1 is the open group:
  rec.record("bake", 0.0, 0.01, parent=zombie_parent, scene_id="s0")
  rec.end(a1)
  assert rec.records[2]["parent"] == a0  # dead attempt, not a1
  tracer = Tracer(clock=clock)
  tr = tracer.start_trace("render")
  root = tr.add_span("dispatch", 0.0, 0.02)
  rec.replay(tr, parent=root)
  tr.finish()
  spans = tr.to_dict()["spans"]
  by_id = {s["id"]: s for s in spans}
  bake = next(s for s in spans if s["name"] == "bake")
  assert by_id[bake["parent"]]["attrs"]["attempt"] == 0


def test_span_recorder_groups_and_replay():
  clock = FakeClock()
  rec = SpanRecorder(clock)
  a = rec.begin("attempt", attempt=0)
  clock.advance(0.01)
  rec.record("bake", 0.0, 0.01, scene_id="s0")
  rec.end(a, error="boom")
  b = rec.begin("attempt", attempt=1)
  clock.advance(0.01)
  rec.end(b)
  tracer = Tracer(clock=clock)
  tr = tracer.start_trace("render")
  root = tr.add_span("dispatch", 0.0, 0.02)
  rec.replay(tr, parent=root)
  tr.finish()
  spans = tr.to_dict()["spans"]
  by_id = {s["id"]: s for s in spans}
  attempts = [s for s in spans if s["name"] == "attempt"]
  assert [a["attrs"]["attempt"] for a in attempts] == [0, 1]
  assert all(by_id[a["parent"]]["name"] == "dispatch" for a in attempts)
  bake = next(s for s in spans if s["name"] == "bake")
  assert by_id[bake["parent"]]["attrs"]["attempt"] == 0
  assert attempts[0]["error"] == "boom" and "error" not in attempts[1]


# --- Prometheus exposition ----------------------------------------------


def _prom_families(svc):
  text = svc.metrics_text()
  return text, parse_metrics_text(text)


PINNED_TYPES = {
    "mpi_serve_uptime_seconds": "gauge",
    "mpi_serve_requests_total": "counter",
    "mpi_serve_batches_total": "counter",
    "mpi_serve_device_render_seconds_total": "counter",
    "mpi_serve_device_phase_seconds_total": "counter",
    "mpi_serve_errors_total": "counter",
    "mpi_serve_rejected_total": "counter",
    "mpi_serve_retries_total": "counter",
    "mpi_serve_watchdog_trips_total": "counter",
    "mpi_serve_fallback_renders_total": "counter",
    "mpi_serve_breaker_opens_total": "counter",
    "mpi_serve_breaker_fastfails_total": "counter",
    "mpi_serve_client_disconnects_total": "counter",
    "mpi_serve_queue_depth": "gauge",
    "mpi_serve_request_latency_seconds": "histogram",
    "mpi_serve_batch_size": "histogram",
    "mpi_serve_cache_hits_total": "counter",
    "mpi_serve_cache_misses_total": "counter",
    "mpi_serve_cache_evictions_total": "counter",
    "mpi_serve_cache_bytes": "gauge",
    "mpi_serve_cache_scenes": "gauge",
    "mpi_serve_breaker_state": "gauge",
    "mpi_serve_breaker_consecutive_failures": "gauge",
}


@pytest.fixture(scope="module")
def loaded_svc():
  """A service that has served a deterministic in-process load."""
  svc = RenderService(max_batch=4, max_wait_ms=50.0, use_mesh=False)
  svc.add_synthetic_scenes(2, height=H, width=W, planes=P)
  futs = [svc.render_async("scene_000", _pose(0.01 * i)) for i in range(3)]
  for f in futs:
    f.result(120)
  svc.render("scene_001", _pose())
  with pytest.raises(KeyError):
    svc.render("nope", _pose())
  yield svc
  svc.close()


def test_metrics_names_types_pinned_and_agree_with_stats(loaded_svc):
  text, families = _prom_families(loaded_svc)
  stats = loaded_svc.stats()
  for name, mtype in PINNED_TYPES.items():
    assert name in families, f"missing {name}\n{text}"
    assert families[name]["type"] == mtype, name
    assert families[name]["help"], name
  def val(family, sample=None, labels=()):
    return families[family]["samples"][(sample or family, tuple(labels))]
  assert val("mpi_serve_requests_total") == stats["requests"]
  assert val("mpi_serve_batches_total") == stats["batches"]
  assert val("mpi_serve_rejected_total") == stats["rejected"]
  assert val("mpi_serve_queue_depth") == stats["queue_depth"]
  for cls in ("transient", "permanent", "deadline"):
    assert val("mpi_serve_errors_total", labels=[("class", cls)]) \
        == stats["errors"][cls]
  for key in ("retries", "watchdog_trips", "fallback_renders",
              "breaker_opens", "breaker_fastfails", "client_disconnects"):
    assert val(f"mpi_serve_{key}_total") == stats["resilience"][key]
  for stat_key, fam in (("hits", "mpi_serve_cache_hits_total"),
                        ("misses", "mpi_serve_cache_misses_total"),
                        ("evictions", "mpi_serve_cache_evictions_total"),
                        ("bytes", "mpi_serve_cache_bytes"),
                        ("scenes", "mpi_serve_cache_scenes")):
    assert val(fam) == stats["cache"][stat_key]
  assert val("mpi_serve_breaker_state",
             labels=[("state", stats["breaker"]["state"])]) == 1
  assert sum(v for (n, _), v in
             families["mpi_serve_breaker_state"]["samples"].items()) == 1


def test_metrics_latency_histogram_cumulative(loaded_svc):
  _, families = _prom_families(loaded_svc)
  stats = loaded_svc.stats()
  hist = families["mpi_serve_request_latency_seconds"]["samples"]
  buckets = sorted(
      ((float(dict(labels)["le"]), v)
       for (name, labels) in hist
       if name.endswith("_bucket")
       for v in [hist[(name, labels)]]),
      key=lambda x: x[0])
  bounds = [b for b, _ in buckets]
  assert bounds == sorted([*LATENCY_BUCKETS_S, float("inf")])
  counts = [c for _, c in buckets]
  assert counts == sorted(counts)  # cumulative: monotone non-decreasing
  count = hist[("mpi_serve_request_latency_seconds_count", ())]
  assert counts[-1] == count == stats["requests"]
  total_s = hist[("mpi_serve_request_latency_seconds_sum", ())]
  assert total_s >= 0


def test_metrics_batch_size_histogram_agrees(loaded_svc):
  _, families = _prom_families(loaded_svc)
  stats = loaded_svc.stats()
  hist = families["mpi_serve_batch_size"]["samples"]
  assert hist[("mpi_serve_batch_size_count", ())] == stats["batches"]
  assert hist[("mpi_serve_batch_size_sum", ())] == stats["requests"]


def test_metrics_device_phases_sum_close_to_render_seconds(loaded_svc):
  stats = loaded_svc.stats()
  phases = stats["device_phase_seconds"]
  assert set(phases) == {"h2d", "compute", "readback"}
  total = sum(phases.values())
  assert total == pytest.approx(stats["device_render_seconds"], abs=0.05)
  assert phases["compute"] > 0


def test_prom_text_renders_without_breaker():
  # resilience=None services have no breaker family — the exposition
  # must degrade, not KeyError.
  m = ServeMetrics()
  text = render_serve_metrics(m.snapshot(cache_stats=None),
                              m.latency_histogram())
  families = parse_metrics_text(text)
  assert "mpi_serve_breaker_state" not in families
  assert "mpi_serve_requests_total" in families


# --- exposition caching (~250 ms TTL) + cluster aggregation --------------


def test_exposition_cache_freshness_and_staleness_bounds():
  clock = FakeClock()
  versions = [0]
  cache = ExpositionCache(lambda: f"v{versions[0]}\n", ttl_s=0.25,
                          clock=clock)
  assert cache.get() == "v0\n"
  versions[0] = 1
  # STALENESS bound: inside the TTL the cached string comes back even
  # though the underlying snapshot changed — and costs zero renders.
  clock.advance(0.249)
  assert cache.get() == "v0\n"
  assert cache.renders == 1 and cache.cache_hits == 1
  # FRESHNESS bound: at/past the TTL the next get re-renders.
  clock.advance(0.002)
  assert cache.get() == "v1\n"
  assert cache.renders == 2
  versions[0] = 2
  cache.invalidate()
  assert cache.get() == "v2\n"  # explicit invalidation skips the TTL


def test_exposition_cache_ttl_zero_disables_caching():
  clock = FakeClock()
  versions = [0]
  cache = ExpositionCache(lambda: f"v{versions[0]}", ttl_s=0.0, clock=clock)
  assert cache.get() == "v0"
  versions[0] = 1
  assert cache.get() == "v1"  # no TTL, no staleness, ever
  assert cache.renders == 2 and cache.cache_hits == 0


def test_render_service_metrics_text_cached_under_injectable_clock():
  clock = FakeClock()
  svc = RenderService(max_batch=2, max_wait_ms=0.5, use_mesh=False,
                      resilience=None, metrics_ttl_s=0.25, clock=clock)
  try:
    svc.add_synthetic_scenes(1, height=H, width=W, planes=P)
    svc.render("scene_000", _pose())
    first = svc.metrics_text()
    svc.render("scene_000", _pose(0.01))
    # A scrape storm inside the window re-reads the same string even
    # though the counters moved...
    clock.advance(0.2)
    assert svc.metrics_text() == first
    # ...and one TTL later the new counters surface.
    clock.advance(0.1)
    families = parse_metrics_text(svc.metrics_text())
    assert families["mpi_serve_requests_total"]["samples"][
        ("mpi_serve_requests_total", ())] == 2
  finally:
    svc.close()


def test_aggregate_metrics_texts_sums_counters_gauges_histograms():
  m1, m2 = ServeMetrics(), ServeMetrics()
  m1.record_request(0.002)
  m1.record_request(0.8)
  m2.record_request(0.002)
  m2.record_rejected()
  t1 = render_serve_metrics(m1.snapshot(), m1.latency_histogram())
  t2 = render_serve_metrics(m2.snapshot(), m2.latency_histogram())
  families = parse_metrics_text(aggregate_metrics_texts([t1, t2]))
  samples = families["mpi_serve_requests_total"]["samples"]
  assert samples[("mpi_serve_requests_total", ())] == 3
  assert families["mpi_serve_rejected_total"]["samples"][
      ("mpi_serve_rejected_total", ())] == 1
  hist = families["mpi_serve_request_latency_seconds"]["samples"]
  assert hist[("mpi_serve_request_latency_seconds_count", ())] == 3
  # Cumulative buckets sum per-bound: both 2 ms requests land <= 0.0025.
  assert hist[("mpi_serve_request_latency_seconds_bucket",
               (("le", "0.0025"),))] == 2
  # HELP/TYPE survive aggregation (Prometheus rejects typeless families).
  assert families["mpi_serve_requests_total"]["type"] == "counter"
  assert families["mpi_serve_requests_total"]["help"]


def test_aggregate_metrics_texts_appends_extra_registry():
  from mpi_vision_tpu.obs import Registry

  reg = Registry()
  reg.gauge("mpi_cluster_backends", "Backends registered.", 3)
  out = aggregate_metrics_texts([], extra=reg)
  families = parse_metrics_text(out)
  assert families["mpi_cluster_backends"]["samples"][
      ("mpi_cluster_backends", ())] == 3


# --- HTTP: X-Trace-Id, /metrics, /debug/traces, /debug/profile ----------


class _FakeProfilerCtx:
  """Stands in for jax.profiler.trace: records entry, optionally blocks."""

  def __init__(self):
    self.dirs = []
    self.entered = threading.Event()
    self.release = threading.Event()
    self.block = False

  @contextlib.contextmanager
  def __call__(self, logdir):
    self.dirs.append(logdir)
    self.entered.set()
    if self.block:
      self.release.wait(30)
    yield


@pytest.fixture(scope="module")
def traced_svc(tmp_path_factory):
  profiler_ctx = _FakeProfilerCtx()
  profiler = DeviceProfiler(
      str(tmp_path_factory.mktemp("prof")), trace_ctx=profiler_ctx,
      sleep=lambda s: None)
  svc = RenderService(max_batch=4, max_wait_ms=20.0, use_mesh=False,
                      tracer=Tracer(), profiler=profiler)
  svc._profiler_ctx = profiler_ctx  # test-side handle
  svc.add_synthetic_scenes(1, height=H, width=W, planes=P)
  httpd = make_http_server(svc, port=0)
  thread = threading.Thread(target=httpd.serve_forever, daemon=True)
  thread.start()
  yield svc, f"http://127.0.0.1:{httpd.server_address[1]}"
  httpd.shutdown()
  svc.close()


def test_http_render_carries_trace_id_and_debug_traces(traced_svc):
  svc, base = traced_svc
  body = json.dumps({"scene_id": "scene_000",
                     "pose": _pose(0.01).tolist()}).encode()
  req = urllib.request.Request(base + "/render", data=body)
  with urllib.request.urlopen(req, timeout=120) as resp:
    tid = resp.headers["X-Trace-Id"]
  assert tid and len(tid) == 16
  traces = json.loads(urllib.request.urlopen(
      base + "/debug/traces", timeout=60).read())
  assert traces["enabled"] is True and traces["finished"] >= 1
  mine = [t for t in traces["recent"] if t["trace_id"] == tid]
  assert len(mine) == 1
  names = {s["name"] for s in mine[0]["spans"]}
  # The acceptance span set: queue-wait, batch-assembly, dispatch with
  # attempt children, readback (+ the bake and device sub-phases).
  assert {"queue_wait", "batch_assembly", "dispatch", "attempt",
          "bake", "h2d", "compute", "readback"} <= names
  by_id = {s["id"]: s for s in mine[0]["spans"]}
  attempt = next(s for s in mine[0]["spans"] if s["name"] == "attempt")
  assert by_id[attempt["parent"]]["name"] == "dispatch"


def test_http_error_response_still_carries_trace_id(traced_svc):
  svc, base = traced_svc
  cases = [
      ({"scene_id": "no_such", "pose": _pose().tolist()}, 404),
      ({"scene_id": "scene_000"}, 400),
  ]
  for payload, want in cases:
    req = urllib.request.Request(base + "/render",
                                 data=json.dumps(payload).encode())
    with pytest.raises(urllib.error.HTTPError) as err:
      urllib.request.urlopen(req, timeout=60)
    assert err.value.code == want
    assert err.value.headers["X-Trace-Id"], payload
  # The 404's trace is recorded with its error.
  snap = svc.tracer.snapshot()
  errored = [t for t in snap["recent"] if t["error"]]
  assert any("no_such" in (t["error"] or "") for t in errored)


def test_http_metrics_endpoint(traced_svc):
  svc, base = traced_svc
  with urllib.request.urlopen(base + "/metrics", timeout=60) as resp:
    assert resp.headers["Content-Type"].startswith("text/plain")
    text = resp.read().decode()
  families = parse_metrics_text(text)
  assert families["mpi_serve_requests_total"]["type"] == "counter"
  stats = svc.stats()
  assert (families["mpi_serve_requests_total"]["samples"][
      ("mpi_serve_requests_total", ())] == stats["requests"])


def test_http_profile_capture_busy_and_validation(traced_svc):
  svc, base = traced_svc
  ctx = svc._profiler_ctx
  out = json.loads(urllib.request.urlopen(
      base + "/debug/profile?seconds=0.05", timeout=60).read())
  assert out["seconds"] == 0.05 and out["logdir"] in ctx.dirs
  # Concurrent capture -> 409 for the second caller.
  ctx.block = True
  ctx.entered.clear()
  errs = {}

  def first():
    try:
      urllib.request.urlopen(base + "/debug/profile?seconds=0.05",
                             timeout=60).read()
    except urllib.error.HTTPError as e:  # pragma: no cover - shouldn't
      errs["first"] = e.code

  t = threading.Thread(target=first, daemon=True)
  t.start()
  assert ctx.entered.wait(30)
  with pytest.raises(urllib.error.HTTPError) as err:
    urllib.request.urlopen(base + "/debug/profile?seconds=0.05",
                           timeout=60)
  assert err.value.code == 409
  ctx.release.set()
  t.join(30)
  ctx.block = False
  assert "first" not in errs
  # Validation: non-numeric and out-of-range seconds are 400s.
  for query in ("seconds=nope", "seconds=-1", "seconds=1e9"):
    with pytest.raises(urllib.error.HTTPError) as err:
      urllib.request.urlopen(base + f"/debug/profile?{query}", timeout=60)
    assert err.value.code == 400, query


def test_http_profile_disabled_is_503():
  svc = RenderService(max_batch=2, max_wait_ms=1.0, use_mesh=False)
  svc.add_synthetic_scenes(1, height=H, width=W, planes=P)
  httpd = make_http_server(svc, port=0)
  thread = threading.Thread(target=httpd.serve_forever, daemon=True)
  thread.start()
  base = f"http://127.0.0.1:{httpd.server_address[1]}"
  try:
    with pytest.raises(urllib.error.HTTPError) as err:
      urllib.request.urlopen(base + "/debug/profile?seconds=1", timeout=60)
    assert err.value.code == 503
    # Tracing disabled: /debug/traces still answers (empty), and renders
    # still get a generated X-Trace-Id.
    traces = json.loads(urllib.request.urlopen(
        base + "/debug/traces", timeout=60).read())
    assert traces["enabled"] is False and traces["recent"] == []
    body = json.dumps({"scene_id": "scene_000",
                       "pose": _pose().tolist()}).encode()
    req = urllib.request.Request(base + "/render", data=body)
    with urllib.request.urlopen(req, timeout=120) as resp:
      assert resp.headers["X-Trace-Id"]
  finally:
    httpd.shutdown()
    svc.close()


def test_profiler_serializes_captures_directly(tmp_path):
  ctx = _FakeProfilerCtx()
  prof = DeviceProfiler(str(tmp_path), trace_ctx=ctx,
                        sleep=lambda s: None)
  with pytest.raises(ValueError):
    prof.capture(0)
  with pytest.raises(ValueError):
    prof.capture(301)
  prof._lock.acquire()
  try:
    assert prof.busy
    with pytest.raises(ProfileBusyError):
      prof.capture(0.01)
  finally:
    prof._lock.release()
  out = prof.capture(0.01)
  assert out["capture"] == 1 and not prof.busy


# --- bake faults produce complete span trees -----------------------------


def test_transient_bake_fault_retries_and_records_bake_error():
  engine = FaultyEngine(RenderEngine(use_mesh=False))
  tracer = Tracer()
  svc = RenderService(
      max_batch=2, max_wait_ms=1.0, engine=engine, tracer=tracer,
      resilience=ResilienceConfig(max_retries=2, backoff_base_s=0.001,
                                  backoff_max_s=0.002),
      cpu_fallback="off")
  svc.add_synthetic_scenes(1, height=H, width=W, planes=P)
  try:
    engine.fail_next_bake(1)  # cold cache: first bake attempt dies
    img, tid = svc.render_traced("scene_000", _pose(), timeout=120)
    assert img.shape == (H, W, 3)
    assert engine.injected["bake"] == 1
    assert svc.stats()["resilience"]["retries"] >= 1
    rec = next(t for t in tracer.snapshot()["recent"]
               if t["trace_id"] == tid)
    assert rec["error"] is None  # the request itself succeeded
    bakes = [s for s in rec["spans"] if s["name"] == "bake"]
    assert len(bakes) == 2  # failed bake + the retry's clean bake
    assert "injected bake fault" in bakes[0]["error"]
    assert "error" not in bakes[1]
    attempts = [s for s in rec["spans"] if s["name"] == "attempt"]
    assert len(attempts) == 2 and attempts[0]["error"]
    by_id = {s["id"]: s for s in rec["spans"]}
    # Each bake nests under its own attempt; the tree stays complete.
    assert [by_id[b["parent"]]["name"] for b in bakes] == \
        ["attempt", "attempt"]
    names = {s["name"] for s in rec["spans"]}
    assert {"queue_wait", "batch_assembly", "dispatch", "readback"} <= names
  finally:
    svc.close()


def test_permanent_bake_fault_fails_request_with_bake_span_error():
  engine = FaultyEngine(RenderEngine(use_mesh=False))
  tracer = Tracer()
  svc = RenderService(
      max_batch=2, max_wait_ms=1.0, engine=engine, tracer=tracer,
      resilience=ResilienceConfig(max_retries=2, backoff_base_s=0.001),
      cpu_fallback="off")
  svc.add_synthetic_scenes(1, height=H, width=W, planes=P)
  try:
    engine.inject_bake(Fault("error", transient=False,
                             message="corrupt MPI payload"))
    with pytest.raises(ValueError, match="corrupt MPI payload"):
      svc.render_traced("scene_000", _pose(), timeout=120)
    assert svc.stats()["resilience"]["retries"] == 0  # permanent: no retry
    rec = tracer.snapshot()["recent"][-1]
    assert "corrupt MPI payload" in rec["error"]
    bake = next(s for s in rec["spans"] if s["name"] == "bake")
    assert "corrupt MPI payload" in bake["error"]
    # A permanent bake failure must not poison the cache: the next
    # request bakes cleanly.
    img = svc.render("scene_000", _pose(), timeout=120)
    assert img.shape == (H, W, 3)
  finally:
    svc.close()


# --- event-log retention (file_sink rotation) ----------------------------


def test_file_sink_rotates_at_max_bytes_and_keeps_k(tmp_path):
  from mpi_vision_tpu.obs.events import EventLog, file_sink

  path = str(tmp_path / "events.jsonl")
  sink = file_sink(path, max_bytes=300, keep=2)
  log = EventLog(sink=sink)
  for i in range(60):
    log.emit("tick", i=i)
  assert sink.rotations >= 2 and sink.rotate_errors == 0
  files = sorted(p.name for p in tmp_path.iterdir())
  # The live file plus at most `keep` rotated generations; no .3 ever.
  assert "events.jsonl" in files and "events.jsonl.1" in files
  assert "events.jsonl.3" not in files
  assert (tmp_path / "events.jsonl").stat().st_size < 300 + 200
  # Every retained line is still intact JSON (rotation never tears one).
  for name in files:
    for line in (tmp_path / name).read_text().splitlines():
      json.loads(line)
  # The newest event survived the rotation churn: it is the last line of
  # the live file, or of ".1" when the final write itself rotated.
  lines = (tmp_path / "events.jsonl").read_text().splitlines() \
      or (tmp_path / "events.jsonl.1").read_text().splitlines()
  assert json.loads(lines[-1])["i"] == 59


def test_file_sink_rotation_failure_is_counted_never_fatal(
    tmp_path, monkeypatch):
  from mpi_vision_tpu.obs import events as events_mod

  path = str(tmp_path / "events.jsonl")
  sink = events_mod.file_sink(path, max_bytes=120, keep=2)
  log = events_mod.EventLog(sink=sink)
  monkeypatch.setattr(events_mod.os, "replace",
                      lambda *a: (_ for _ in ()).throw(OSError("disk")))
  for i in range(20):
    log.emit("tick", i=i)  # must not raise
  assert sink.rotate_errors > 0
  # The sink never raised into the log (rotation is not a sink error)
  # and events kept landing in the (over-size) live file.
  assert log.sink_errors == 0
  lines = (tmp_path / "events.jsonl").read_text().splitlines()
  assert json.loads(lines[-1])["i"] == 19


def test_file_sink_validates_retention_knobs(tmp_path):
  from mpi_vision_tpu.obs.events import file_sink

  with pytest.raises(ValueError, match="max_bytes"):
    file_sink(str(tmp_path / "e.jsonl"), max_bytes=0)
  with pytest.raises(ValueError, match="keep"):
    file_sink(str(tmp_path / "e.jsonl"), max_bytes=100, keep=0)
