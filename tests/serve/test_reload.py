"""Live checkpoint reload: watcher semantics + zero-drop scene swaps.

The acceptance pin for the train -> serve loop's last edge: a checkpoint
published WHILE the service is under load swaps the scenes in place with
zero failed in-flight requests — requests racing the swap serve either
the old bake or the new one, never an error, never a mix. The watcher
itself is pinned on a fake store (fire-once per step, failed reloads
retried, stale steps ignored) and against a real ``CheckpointStore``
whose publishes are atomic renames a concurrent poll can race safely.
"""

import threading
import time

import numpy as np
import pytest

from mpi_vision_tpu.ckpt import CheckpointStore, CheckpointWatcher
from mpi_vision_tpu.serve import RenderService, synthetic_scene

H = W = 16
P = 4


def _pose(tx=0.0):
  pose = np.eye(4, dtype=np.float32)
  pose[0, 3] = tx
  return pose


# --- watcher unit behavior (fake store) ----------------------------------


class FakeStore:
  def __init__(self, step=None):
    self.step = step
    self.boom = None

  def latest_step(self):
    if self.boom is not None:
      raise self.boom
    return self.step


def test_watcher_fires_once_per_new_step_and_ignores_stale():
  store = FakeStore(step=None)
  fired = []
  w = CheckpointWatcher(store, fired.append, poll_s=1.0)
  assert w.check_once() is None  # empty store: nothing to do
  store.step = 5
  assert w.check_once() == 5
  assert w.check_once() is None  # same step: fire-once
  store.step = 4
  assert w.check_once() is None  # regression (GC'd newest): stale, ignored
  store.step = 7
  assert w.check_once() == 7
  assert fired == [5, 7]
  assert w.snapshot()["reloads"] == 2


def test_watcher_initial_step_suppresses_the_startup_checkpoint():
  store = FakeStore(step=5)
  fired = []
  w = CheckpointWatcher(store, fired.append, initial_step=5)
  assert w.check_once() is None  # step 5 was the startup bake
  store.step = 6
  assert w.check_once() == 6
  assert fired == [6]


def test_watcher_failed_reload_is_retried_until_superseded():
  store = FakeStore(step=3)
  calls = []

  def flaky(step):
    calls.append(step)
    if len(calls) < 3:
      raise RuntimeError("bake failed")

  logs = []
  w = CheckpointWatcher(store, flaky, log=logs.append)
  assert w.check_once() is None  # fails; step 3 stays unseen
  assert w.check_once() is None  # retried next poll
  assert w.check_once() == 3     # third time lucky
  assert calls == [3, 3, 3]
  snap = w.snapshot()
  assert snap["reload_errors"] == 2 and snap["reloads"] == 1
  assert snap["last_error"] is None  # cleared by the success
  assert any("step 3 failed" in line for line in logs)


def test_watcher_store_errors_counted_not_fatal():
  store = FakeStore(step=1)
  w = CheckpointWatcher(store, lambda s: None)
  store.boom = OSError("transient NFS sadness")
  assert w.check_once() is None
  assert w.snapshot()["reload_errors"] == 1
  store.boom = None
  assert w.check_once() == 1  # recovered


def test_watcher_thread_polls_and_stops():
  store = FakeStore(step=None)
  fired = []
  with CheckpointWatcher(store, fired.append, poll_s=0.01).start() as w:
    store.step = 2
    deadline = time.monotonic() + 5.0
    while not fired and time.monotonic() < deadline:
      time.sleep(0.01)
  assert fired == [2]
  assert w.snapshot()["polls"] >= 1


# --- zero-drop swap under load ------------------------------------------


def test_swap_scenes_invalidates_both_caches_and_changes_pixels():
  with RenderService(max_batch=2, max_wait_ms=0.5, use_mesh=False,
                     resilience=None) as svc:
    svc.add_scene("s", *synthetic_scene("s", H, W, P, seed=0))
    before = svc.render("s", _pose())
    assert svc.cache.stats()["misses"] == 1
    svc.swap_scenes({"s": synthetic_scene("s", H, W, P, seed=99)})
    after = svc.render("s", _pose())
    stats = svc.cache.stats()
    assert stats["invalidations"] == 1 and stats["misses"] == 2  # re-baked
    assert not np.array_equal(before, after)  # really the new data
    # And the new bake matches a service that NEVER saw the old data.
    with RenderService(max_batch=2, max_wait_ms=0.5, use_mesh=False,
                       resilience=None) as fresh:
      fresh.add_scene("s", *synthetic_scene("s", H, W, P, seed=99))
      np.testing.assert_array_equal(after, fresh.render("s", _pose()))


def test_ckpt_publish_swaps_scenes_with_zero_failed_inflight(tmp_path):
  """The acceptance pin: checkpoint publishes arrive while requests are
  in flight; every request succeeds (old scenes or new, never an error)
  and the pixels eventually serve the newest publish."""
  store = CheckpointStore(str(tmp_path))
  store.save(0, {"v": np.float32(0)})

  with RenderService(max_batch=4, max_wait_ms=0.5, use_mesh=False,
                     resilience=None) as svc:
    scene_ids = ["ckpt_000", "ckpt_001"]
    for sid in scene_ids:
      svc.add_scene(sid, *synthetic_scene(sid, H, W, P, seed=0))

    def reload_step(step):
      # The CLI's _reload in miniature: derive new scene data from the
      # published step, swap in place under the SAME ids, prebaked so
      # the first post-swap request skips the bake too.
      svc.swap_scenes({sid: synthetic_scene(sid, H, W, P, seed=step)
                       for sid in scene_ids}, prebake=True)

    watcher = CheckpointWatcher(store, reload_step, poll_s=1.0,
                                initial_step=0)
    stop = threading.Event()
    failures: list[BaseException] = []
    completed = [0]
    lock = threading.Lock()

    def hammer(widx):
      i = 0
      while not stop.is_set():
        sid = scene_ids[(widx + i) % len(scene_ids)]
        i += 1
        try:
          img = svc.render(sid, _pose(0.001 * (i % 7)), timeout=60)
          assert img.shape == (H, W, 3)
        except BaseException as e:  # noqa: BLE001 - ANY failure is the bug
          with lock:
            failures.append(e)
          return
        with lock:
          completed[0] += 1

    threads = [threading.Thread(target=hammer, args=(w,), daemon=True)
               for w in range(4)]
    for t in threads:
      t.start()
    deadline = time.monotonic() + 60.0
    for step in (1, 2, 3):
      # A real publish (atomic rename) lands mid-traffic...
      while completed[0] < step * 20 and time.monotonic() < deadline:
        time.sleep(0.005)
      store.save(step, {"v": np.float32(step)})
      assert watcher.check_once() == step  # ...and the watcher swaps it in.
    while completed[0] < 80 and time.monotonic() < deadline:
      time.sleep(0.005)
    stop.set()
    for t in threads:
      t.join(30)

    assert not failures, f"in-flight requests failed across swaps: " \
                         f"{failures[:3]}"
    assert completed[0] >= 80
    assert watcher.snapshot()["reloads"] == 3
    # The service now provably serves step 3's data.
    got = svc.render(scene_ids[0], _pose())
    with RenderService(max_batch=2, max_wait_ms=0.5, use_mesh=False,
                       resilience=None) as fresh:
      fresh.add_scene(scene_ids[0],
                      *synthetic_scene(scene_ids[0], H, W, P, seed=3))
      np.testing.assert_array_equal(got, fresh.render(scene_ids[0],
                                                      _pose()))


def test_swap_scenes_prebake_leaves_no_cold_first_request():
  with RenderService(max_batch=2, max_wait_ms=0.5, use_mesh=False,
                     resilience=None) as svc:
    svc.add_scene("s", *synthetic_scene("s", H, W, P, seed=0))
    svc.render("s", _pose())
    svc.swap_scenes({"s": synthetic_scene("s", H, W, P, seed=1)},
                    prebake=True)
    misses_after_swap = svc.cache.stats()["misses"]
    svc.render("s", _pose())  # must be a cache HIT on the new bake
    stats = svc.cache.stats()
    assert stats["misses"] == misses_after_swap
    assert stats["hits"] >= 1
