"""Multi-device serving on the 8-device CPU mesh (in-process).

Runs the scheduler + sharded engine dispatch on the suite's own
8-device mesh (tests/conftest.py forces it for every test process) and
asserts the batching invariant at full strength: coalesced batched
results are bit-for-bit equal (f32) to per-request renders dispatched
one at a time through the same sharded engine. Against a
*single-device* engine the sharded render is allclose but NOT bitwise —
XLA compiles the shard_map program separately and f32 rounding differs
in the last ulp — so the cross-engine check is atol=1e-5 (same
tolerance as test_parallel.py).

This used to spawn a subprocess for interpreter hygiene; the service
closes its scheduler threads on ``close()`` and the jit cache is keyed
by shape, so in-process costs nothing and saves the ~per-test
interpreter + jax import (tier-1 seconds are the scarce resource).
"""

import numpy as np

from mpi_vision_tpu.serve import RenderEngine, RenderService


def test_sharded_serving_batches_bit_for_bit():
  svc = RenderService(max_batch=8, max_wait_ms=500.0, use_mesh=True)
  try:
    svc.add_synthetic_scenes(1, height=16, width=16, planes=4)
    assert svc.engine.describe()["devices"] == 8, svc.engine.describe()

    poses = []
    for i in range(8):
      p = np.eye(4, dtype=np.float32)
      p[0, 3], p[2, 3] = 0.01 * i, -0.005 * i
      poses.append(p)

    # 8 concurrent requests -> one coalesced sharded dispatch.
    before = svc.engine.dispatches
    futs = [svc.render_async("scene_000", p) for p in poses]
    outs = [f.result(600) for f in futs]
    assert svc.engine.dispatches - before == 1, svc.engine.dispatches
    hist = svc.stats()["batch_size_hist"]
    assert max(int(k) for k in hist) >= 2, hist

    # Bit-for-bit (f32): batched == per-request through the same engine.
    for pose, out in zip(poses, outs):
      assert out.dtype == np.float32, out.dtype
      solo = svc.render("scene_000", pose)
      assert np.array_equal(out, solo), float(np.abs(out - solo).max())

    # Cross-engine: sharded matches a single-device engine to f32 noise.
    single = RenderEngine(use_mesh=False)
    scene = svc.cache.get("scene_000")
    for pose, out in zip(poses, outs):
      ref = single.render_one(scene, pose)
      np.testing.assert_allclose(out, ref, atol=1e-5)
  finally:
    svc.close()
