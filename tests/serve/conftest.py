"""Shared serve-tier fixtures: ONE live backend pool for every suite.

The three live multi-process suites (test_cluster, test_gossip,
test_supervisor) each used to spawn their own BackendPool — three full
JAX child-process spawn arcs per tier-1 run, the single most expensive
setup in the suite. The pools were near-identical (same image size and
plane count, pixels a pure function of ``(seed, scene_id)``), and every
suite asserts against its OWN router/supervisor state, never against
backend-side absolute counters — so one session-scoped pool serves all
three.

Sharing a pool across chaos suites needs one discipline: a suite that
SIGKILLs backends may leave a corpse behind (a failed assertion skips
the restore path). ``heal_pool`` re-gates the fleet — every module
fixture calls it before building its router, so each suite starts from
three live, healthy backends regardless of what the previous one did.
"""

import os
import sys

import pytest

from mpi_vision_tpu.serve.cluster import BackendPool

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

N_BACKENDS = 3
N_SCENES = 6
IMG, PLANES = 32, 4


def _pool_env():
  sys.path.insert(0, REPO)
  from _cpu_mesh import hardened_env

  env = hardened_env(1)
  env["JAX_COMPILATION_CACHE_DIR"] = os.path.join(REPO, ".jax_cache")
  return env


def heal_pool(pool) -> dict:
  """Restart any backend a previous suite's chaos left dead and return
  the (unchanged — restarts reuse ports) address map."""
  for bid in sorted(pool.addresses()):
    if not pool.alive(bid):
      pool.restart(bid)
  return pool.addresses()


@pytest.fixture(scope="session")
def backend_pool():
  """3 real serve processes shared by every live suite in tests/serve."""
  pool = BackendPool(
      N_BACKENDS, scenes=N_SCENES, img_size=IMG, planes=PLANES,
      env=_pool_env(),
      extra_args=["--max-batch", "4", "--max-wait-ms", "1"],
      log=lambda m: print(m, file=sys.stderr))
  try:
    pool.start()
  except Exception:
    pool.close()
    raise
  yield pool
  pool.close()


@pytest.fixture(scope="module")
def healed_backends(backend_pool):
  """``(pool, addresses)`` with every backend re-gated live — what a
  suite's module fixture consumes (fresh heal per module, one pool)."""
  return backend_pool, heal_pool(backend_pool)
