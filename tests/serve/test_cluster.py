"""serve/cluster tests: ring placement, router failover, the real pool.

Three layers, cheapest first:

  * ``HashRing`` unit tests — placement determinism, replication,
    minimal movement on resize (the satellite pin: re-placement after a
    pool resize is a pure function, not an accident of dict order).
  * ``Router`` tests over an injectable fake transport — per-backend
    breaker isolation, failover order, the 502-never-500 contract for
    malformed/truncated backend bodies, resurrection through the
    half-open probe, aggregated /healthz / /metrics — all deterministic
    (fake clocks, no sockets except the router's own front end).
  * The multi-process acceptance test — ≥3 REAL ``serve`` child
    processes (BackendPool), ≥6 scenes sharded across them,
    bit-identical routed renders, a SIGKILL mid-load with failover +
    breaker isolation + degraded-not-unhealthy aggregation, and
    router->backend trace stitching via the outbound W3C traceparent.
"""

import base64
import json
import os
import sys
import threading
import urllib.request

import numpy as np
import pytest

from mpi_vision_tpu.obs import Tracer, parse_metrics_text
from mpi_vision_tpu.serve.cluster import (
    AllReplicasOpenError,
    BackendPool,
    HashRing,
    ReplicasExhaustedError,
    Router,
    make_router_http_server,
)

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


# --- ring ----------------------------------------------------------------


SCENES_100 = [f"scene_{i:03d}" for i in range(100)]


def test_ring_placement_deterministic_and_order_free():
  a = HashRing(["x", "y", "z"], replication=2)
  b = HashRing(["z", "x", "y"], replication=2)  # insertion order differs
  for sid in SCENES_100:
    assert a.placement(sid) == b.placement(sid)
    assert len(a.placement(sid)) == 2
    assert len(set(a.placement(sid))) == 2  # replicas are distinct


def test_ring_replication_clamped_to_pool_size():
  ring = HashRing(["only"], replication=3)
  assert ring.placement("s") == ["only"]
  assert HashRing([], replication=2).placement("s") == []


def test_ring_spreads_scenes_across_backends():
  ring = HashRing(["a", "b", "c"], replication=1)
  primaries = {ring.primary(sid) for sid in SCENES_100}
  assert primaries == {"a", "b", "c"}  # nobody owns everything


def test_ring_resize_moves_only_scenes_touching_the_changed_backend():
  before = HashRing(["a", "b", "c"], replication=2)
  grown = HashRing(["a", "b", "c", "d"], replication=2)
  moved = 0
  for sid in SCENES_100:
    if "d" not in grown.placement(sid):
      # Consistent hashing: adding d only remaps scenes d now serves.
      assert grown.placement(sid) == before.placement(sid)
    else:
      moved += 1
  assert 0 < moved < len(SCENES_100)  # d took some load, not all of it
  # Removal is exactly the inverse: the survivor ring is bit-identical
  # to one built without the backend (re-placement is deterministic).
  shrunk = HashRing(["a", "b", "c", "d"], replication=2)
  shrunk.remove("d")
  for sid in SCENES_100:
    assert shrunk.placement(sid) == before.placement(sid)


# --- router over a fake transport ---------------------------------------


class FakeTransport:
  """address -> handler(method, path, body, headers) -> (status, headers,
  body); raising ConnectionError simulates a dead host. Records calls."""

  def __init__(self):
    self.handlers = {}
    self.calls = []

  def set(self, address, handler):
    self.handlers[address] = handler

  def request(self, method, url, body=None, headers=None, timeout=30.0):
    assert url.startswith("http://")
    address, _, path = url[len("http://"):].partition("/")
    self.calls.append((address, method, "/" + path))
    return self.handlers[address](method, "/" + path, body, headers or {})


def _good_render(scene_id, h=2, w=2, fill=0.5):
  img = np.full((h, w, 3), fill, np.float32)
  body = json.dumps({
      "scene_id": scene_id, "shape": [h, w, 3], "dtype": "<f4",
      "image_b64": base64.b64encode(img.tobytes()).decode(),
  }).encode()
  return 200, {"Content-Type": "application/json"}, body


def _dead(method, path, body, headers):
  raise ConnectionError("connection refused")


class FakeClock:
  def __init__(self, t=100.0):
    self.t = t

  def __call__(self):
    return self.t


def _two_backend_router(transport, clock=None, threshold=2, reset_s=10.0,
                        tracer=None):
  return Router({"a": "hostA:1", "b": "hostB:1"}, replication=2,
                breaker_threshold=threshold, breaker_reset_s=reset_s,
                transport=transport,
                clock=clock if clock is not None else FakeClock(),
                tracer=tracer)


def _scene_with_primary(router, primary):
  sid = next(s for s in SCENES_100 if router.placement(s)[0] == primary)
  body = json.dumps({"scene_id": sid, "pose": np.eye(4).tolist()}).encode()
  return sid, body


def test_router_forwards_to_primary_and_carries_traceparent():
  transport = FakeTransport()
  seen = {}

  def handler(method, path, body, headers):
    seen.update(headers)
    return _good_render("s")

  transport.set("hostA:1", handler)
  transport.set("hostB:1", handler)
  router = _two_backend_router(transport)
  sid, body = _scene_with_primary(router, "a")
  status, headers, _ = router.forward_render(sid, body, trace_id="ab" * 16)
  assert status == 200
  assert headers["X-Backend-Id"] == "a"
  assert len(transport.calls) == 1  # primary answered; no failover
  # Outbound W3C traceparent: version 00, OUR trace id, sampled.
  version, trace_id, span_id, flags = seen["traceparent"].split("-")
  assert (version, trace_id, flags) == ("00", "ab" * 16, "01")
  assert len(span_id) == 16


def test_router_fails_over_to_replica_when_primary_is_dead():
  transport = FakeTransport()
  transport.set("hostA:1", _dead)
  transport.set("hostB:1", lambda m, p, b, h: _good_render("s"))
  router = _two_backend_router(transport)
  sid, body = _scene_with_primary(router, "a")
  status, headers, _ = router.forward_render(sid, body)
  assert status == 200 and headers["X-Backend-Id"] == "b"
  snap = router.metrics.snapshot()
  assert snap["failovers"] == 1 and snap["forwards"] == {"b": 1}
  assert router.stats()["backend_info"]["a"]["breaker"][
      "consecutive_failures"] == 1


def test_router_4xx_passthrough_is_not_a_backend_failure():
  transport = FakeTransport()
  err = json.dumps({"error": "unknown scene"}).encode()
  transport.set("hostA:1", lambda m, p, b, h: (404, {}, err))
  transport.set("hostB:1", lambda m, p, b, h: (404, {}, err))
  router = _two_backend_router(transport)
  sid, body = _scene_with_primary(router, "a")
  status, headers, resp = router.forward_render(sid, body)
  assert status == 404 and resp == err
  assert len(transport.calls) == 1  # a 404 is an ANSWER: no failover
  assert router.stats()["backend_info"]["a"]["breaker"][
      "consecutive_failures"] == 0  # and the backend counts as healthy


@pytest.mark.parametrize("bad_response", [
    lambda: (200, {"Content-Type": "application/json"}, b"not json {"),
    lambda: (200, {"Content-Type": "application/json"},
             json.dumps({"scene_id": "s"}).encode()),  # missing keys
    lambda: _truncated_json(),
    lambda: (200, {"Content-Type": "application/octet-stream",
                   "X-Image-Shape": "2,2,3", "X-Image-Dtype": "<f4"},
             b"\x00" * 17),  # truncated binary: shape says 48 bytes
    lambda: (200, {"Content-Type": "application/octet-stream",
                   "X-Image-Shape": "nope", "X-Image-Dtype": "<f4"},
             b"\x00" * 48),
])
def test_router_rejects_garbage_200s_and_fails_over(bad_response):
  transport = FakeTransport()
  transport.set("hostA:1", lambda m, p, b, h: bad_response())
  transport.set("hostB:1", lambda m, p, b, h: _good_render("s"))
  router = _two_backend_router(transport)
  sid, body = _scene_with_primary(router, "a")
  status, headers, _ = router.forward_render(sid, body)
  assert status == 200 and headers["X-Backend-Id"] == "b"
  snap = router.metrics.snapshot()
  assert snap["bad_responses"] == 1 and snap["failovers"] == 1


def _truncated_json():
  full = json.dumps({
      "scene_id": "s", "shape": [2, 2, 3], "dtype": "<f4",
      "image_b64": base64.b64encode(b"\x00" * 48).decode()}).encode()
  return 200, {"Content-Type": "application/json"}, full[:-20]


def test_router_breaker_opens_and_isolates_only_the_bad_backend():
  transport = FakeTransport()
  transport.set("hostA:1", _dead)
  transport.set("hostB:1", lambda m, p, b, h: _good_render("s"))
  clock = FakeClock()
  router = _two_backend_router(transport, clock=clock, threshold=2)
  sid, body = _scene_with_primary(router, "a")
  for _ in range(2):  # two failed attempts open a's circuit
    status, _, _ = router.forward_render(sid, body)
    assert status == 200  # the replica still answers every time
  info = router.stats()["backend_info"]
  assert info["a"]["breaker"]["state"] == "open"
  assert info["b"]["breaker"]["state"] == "closed"  # isolation
  transport.calls.clear()
  status, headers, _ = router.forward_render(sid, body)
  assert status == 200 and headers["X-Backend-Id"] == "b"
  # The open breaker means the corpse is not even contacted.
  assert all(addr != "hostA:1" for addr, _, _ in transport.calls)


def test_router_resurrected_backend_recloses_via_half_open_probe():
  transport = FakeTransport()
  transport.set("hostA:1", _dead)
  transport.set("hostB:1", lambda m, p, b, h: _good_render("s"))
  clock = FakeClock()
  router = _two_backend_router(transport, clock=clock, threshold=2,
                               reset_s=10.0)
  sid, body = _scene_with_primary(router, "a")
  for _ in range(2):
    router.forward_render(sid, body)
  assert router.stats()["backend_info"]["a"]["breaker"]["state"] == "open"
  # The backend comes back; after the cooldown the NEXT request is the
  # half-open probe, and its success re-closes the circuit.
  transport.set("hostA:1", lambda m, p, b, h: _good_render("s"))
  clock.t += 10.1
  status, headers, _ = router.forward_render(sid, body)
  assert status == 200 and headers["X-Backend-Id"] == "a"
  assert router.stats()["backend_info"]["a"]["breaker"]["state"] == "closed"


def test_router_all_replicas_open_is_503_with_retry_after():
  transport = FakeTransport()
  transport.set("hostA:1", _dead)
  transport.set("hostB:1", _dead)
  clock = FakeClock()
  router = _two_backend_router(transport, clock=clock, threshold=1)
  sid, body = _scene_with_primary(router, "a")
  with pytest.raises(ReplicasExhaustedError):
    router.forward_render(sid, body)  # opens both breakers (threshold 1)
  with pytest.raises(AllReplicasOpenError) as err:
    router.forward_render(sid, body)
  assert 0 < err.value.retry_after_s <= 10.0
  assert router.metrics.snapshot()["breaker_fastfails"] == 1


# --- eject/readmit (the supervisor's administrative hooks) ---------------


def test_router_ejected_backend_is_skipped_without_an_attempt():
  transport = FakeTransport()
  transport.set("hostA:1", lambda m, p, b, h: _good_render("s"))
  transport.set("hostB:1", lambda m, p, b, h: _good_render("s"))
  router = _two_backend_router(transport)
  sid, body = _scene_with_primary(router, "a")
  router.eject("a", reason="rolling_restart")
  status, headers, _ = router.forward_render(sid, body)
  assert status == 200 and headers["X-Backend-Id"] == "b"
  # Planned downtime spends NOTHING: no attempt, no failover, no
  # breaker count against the ejected backend.
  assert all(addr != "hostA:1" for addr, _, _ in transport.calls)
  snap = router.metrics.snapshot()
  assert snap["failovers"] == 0
  info = router.stats()["backend_info"]
  assert info["a"]["breaker"]["consecutive_failures"] == 0
  assert info["a"]["ejected"] is True
  assert router.ejected() == ["a"]
  router.readmit("a")
  assert router.ejected() == []
  transport.calls.clear()
  status, headers, _ = router.forward_render(sid, body)
  assert status == 200 and headers["X-Backend-Id"] == "a"
  # Both edges land in the lifecycle log.
  kinds = router.events.snapshot()["by_kind"]
  assert kinds["backend_eject"] == 1 and kinds["backend_readmit"] == 1


def test_router_all_replicas_ejected_is_503_not_error():
  transport = FakeTransport()
  transport.set("hostA:1", lambda m, p, b, h: _good_render("s"))
  transport.set("hostB:1", lambda m, p, b, h: _good_render("s"))
  router = _two_backend_router(transport)
  sid, body = _scene_with_primary(router, "a")
  router.eject("a")
  router.eject("b")
  with pytest.raises(AllReplicasOpenError):
    router.forward_render(sid, body)
  assert router.metrics.snapshot()["breaker_fastfails"] == 1


# --- retry budget (failover amplification guard) -------------------------


def test_router_retry_budget_degrades_brownout_to_fast_503():
  from mpi_vision_tpu.serve.cluster import RetryBudgetExhaustedError

  transport = FakeTransport()
  transport.set("hostA:1", _dead)
  transport.set("hostB:1", _dead)
  # Breakers never open (high threshold): the budget is the only guard.
  router = Router({"a": "hostA:1", "b": "hostB:1"}, replication=2,
                  breaker_threshold=1000, transport=transport,
                  clock=FakeClock(), retry_budget_ratio=0.1,
                  retry_budget_initial=2.0)
  sid, body = _scene_with_primary(router, "a")
  # 2 initial tokens cover the first two requests' failovers (each walk
  # = 1 primary attempt + 1 budgeted failover).
  for _ in range(2):
    with pytest.raises(ReplicasExhaustedError):
      router.forward_render(sid, body)
  # Bucket dry (2 withdrawn, deposits only 0.1/request): the walk now
  # stops after the primary attempt — fast 503, no amplification.
  calls_before = len(transport.calls)
  with pytest.raises(RetryBudgetExhaustedError):
    router.forward_render(sid, body)
  assert len(transport.calls) == calls_before + 1  # primary only
  snap = router.metrics.snapshot()
  assert snap["retry_budget_exhausted"] == 1
  budget = router.stats()["retry_budget"]
  assert budget["withdrawals"] == 2 and budget["refused"] == 1
  assert budget["tokens"] < 1.0


def test_router_retry_budget_refusal_releases_a_claimed_probe_slot():
  """A dry budget can interrupt the walk right after allow_primary()
  claimed a replica's half-open probe; the slot must be released or
  that breaker wedges in HALF_OPEN forever (nothing else feeds it)."""
  from mpi_vision_tpu.serve.cluster import RetryBudgetExhaustedError

  transport = FakeTransport()
  transport.set("hostA:1", _dead)
  transport.set("hostB:1", _dead)
  clock = FakeClock()
  router = Router({"a": "hostA:1", "b": "hostB:1"}, replication=2,
                  breaker_threshold=1, breaker_reset_s=10.0,
                  transport=transport, clock=clock,
                  retry_budget_ratio=0.4, retry_budget_initial=1.0)
  sid, body = _scene_with_primary(router, "a")
  with pytest.raises(ReplicasExhaustedError):
    router.forward_render(sid, body)  # opens both breakers, spends the token
  clock.t += 10.1  # both cooldowns elapse: the next walk probes
  with pytest.raises(RetryBudgetExhaustedError):
    # a's probe fails (dead, re-opens a), b's allow_primary() claims ITS
    # probe slot, then the dry budget stops the walk before the attempt.
    router.forward_render(sid, body)
  # Deposits refilled the bucket past 1 token; b's next allow_primary()
  # must still probe — a leaked slot would keep it False forever (and a,
  # freshly re-opened, stays skipped: b IS the serving path).
  transport.set("hostB:1", lambda m, p, b, h: _good_render("s"))
  status, headers, _ = router.forward_render(sid, body)
  assert status == 200 and headers["X-Backend-Id"] == "b"
  assert router.stats()["backend_info"]["b"]["breaker"]["state"] == "closed"


def test_router_retry_budget_refills_from_good_traffic():
  transport = FakeTransport()
  transport.set("hostA:1", lambda m, p, b, h: _good_render("s"))
  transport.set("hostB:1", lambda m, p, b, h: _good_render("s"))
  router = Router({"a": "hostA:1", "b": "hostB:1"}, replication=2,
                  transport=transport, clock=FakeClock(),
                  retry_budget_ratio=0.5, retry_budget_initial=0.0)
  sid, body = _scene_with_primary(router, "a")
  for _ in range(4):  # 4 * 0.5 = 2 tokens earned
    assert router.forward_render(sid, body)[0] == 200
  transport.set("hostA:1", _dead)
  status, headers, _ = router.forward_render(sid, body)
  assert status == 200 and headers["X-Backend-Id"] == "b"  # budgeted


# --- load-aware replica choice -------------------------------------------


def _load_router(transport, clock):
  return Router({"a": "hostA:1", "b": "hostB:1"}, replication=2,
                transport=transport, clock=clock, load_aware=True,
                load_ttl_s=5.0, load_threshold=4)


def test_router_load_aware_demotes_deep_primary():
  transport = FakeTransport()
  transport.set("hostA:1", lambda m, p, b, h: _good_render("s"))
  transport.set("hostB:1", lambda m, p, b, h: _good_render("s"))
  clock = FakeClock()
  router = _load_router(transport, clock)
  sid, body = _scene_with_primary(router, "a")
  # No load data: placement order wins (cache locality).
  assert router.forward_render(sid, body)[1]["X-Backend-Id"] == "a"
  # Fresh depths show the primary 9 deep vs 0: demote it.
  router.note_backend_load("a", 9)
  router.note_backend_load("b", 0)
  status, headers, _ = router.forward_render(sid, body)
  assert status == 200 and headers["X-Backend-Id"] == "b"
  assert router.metrics.snapshot()["load_reroutes"] == 1
  # Below the threshold: the primary keeps its scene.
  router.note_backend_load("a", 3)
  assert router.forward_render(sid, body)[1]["X-Backend-Id"] == "a"


def test_router_cell_routing_spreads_and_counts_reroutes():
  """Tile-granular routing (serve/tiles.py x the edge lattice): with
  --route-cell on, requests place by their (scene, view-cell) ring key
  — a hot scene's cells spread over the pool, reroutes off the
  scene-level primary are counted, and a malformed pose falls back to
  the scene key instead of failing placement."""
  transport = FakeTransport()
  transport.set("hostA:1", lambda m, p, b, h: _good_render("s"))
  transport.set("hostB:1", lambda m, p, b, h: _good_render("s"))
  router = Router({"a": "hostA:1", "b": "hostB:1"}, replication=1,
                  route_cell=0.05, transport=transport, clock=FakeClock())
  sid = "hot"
  rng = np.random.default_rng(7)
  served = set()
  for _ in range(24):
    pose = np.eye(4, dtype=np.float32)
    pose[:3, 3] = rng.uniform(-1.0, 1.0, 3).astype(np.float32)
    req = {"scene_id": sid, "pose": pose.tolist()}
    cell = router.request_cell(req)
    assert cell is not None
    status, headers, _ = router.forward_render(
        sid, json.dumps(req).encode(), cell=cell)
    assert status == 200
    served.add(headers["X-Backend-Id"])
  # One scene, replication 1: without cell keys ONE backend serves
  # everything; with them both backends took cells.
  assert served == {"a", "b"}
  snap = router.metrics.snapshot()
  assert snap["cell_routes"] == 24
  assert 0 < snap["cell_reroutes"] < 24
  # Same cell -> same placement (determinism the edge caches rely on).
  pose = np.eye(4, dtype=np.float32)
  req = {"scene_id": sid, "pose": pose.tolist()}
  assert (router.request_cell(req) == router.request_cell(req))
  # Malformed/missing poses ride the scene-level key (the backend owns
  # the 400; the router must not fail in placement math).
  assert router.request_cell({"scene_id": sid, "pose": "junk"}) is None
  assert router.request_cell({"scene_id": sid}) is None
  off = Router({"a": "hostA:1"}, transport=transport, clock=FakeClock())
  assert off.request_cell(req) is None  # routing off: scene-level key


def test_router_load_aware_ignores_stale_depths():
  transport = FakeTransport()
  transport.set("hostA:1", lambda m, p, b, h: _good_render("s"))
  transport.set("hostB:1", lambda m, p, b, h: _good_render("s"))
  clock = FakeClock()
  router = _load_router(transport, clock)
  sid, body = _scene_with_primary(router, "a")
  router.note_backend_load("a", 9)
  router.note_backend_load("b", 0)
  clock.t += 5.1  # past load_ttl_s: yesterday's hotspot is not today's
  assert router.forward_render(sid, body)[1]["X-Backend-Id"] == "a"
  assert router.metrics.snapshot()["load_reroutes"] == 0


def test_router_stats_fanout_feeds_the_load_table():
  transport = FakeTransport()

  def statsy(depth):
    def handler(method, path, body, headers):
      if path == "/stats":
        return 200, {}, json.dumps({"queue_depth": depth}).encode()
      return 200, {}, json.dumps({"status": "ok"}).encode()
    return handler

  transport.set("hostA:1", statsy(7))
  transport.set("hostB:1", statsy(1))
  clock = FakeClock()
  router = _load_router(transport, clock)
  router.stats()  # any stats scrape doubles as a load refresh
  with router._lock:
    depths = {b: d for b, (d, _) in router._load.items()}
  assert depths == {"a": 7.0, "b": 1.0}


# --- router-side client-perceived SLO ------------------------------------


def test_router_slo_counts_failures_the_backends_never_see():
  """Client-perceived availability: a 502 from an exhausted replica walk
  is a failure NO backend tracker recorded (the backends were dead) —
  the router's own SloTracker must count it, next to the successes."""
  transport = FakeTransport()
  transport.set("hostA:1", lambda m, p, b, h: _good_render("s"))
  transport.set("hostB:1", lambda m, p, b, h: _good_render("s"))
  router = _two_backend_router(transport)
  sid, body = _scene_with_primary(router, "a")
  for _ in range(8):
    router.forward_render(sid, body)
  transport.set("hostA:1", _dead)
  transport.set("hostB:1", _dead)
  for _ in range(3):
    # The first walk exhausts the replicas (502); the failures open both
    # breakers, so later walks fast-fail (503) — ALL are client-
    # perceived failures the backend trackers never saw.
    with pytest.raises((ReplicasExhaustedError, AllReplicasOpenError)):
      router.forward_render(sid, body)
  snap = router.slo.snapshot()
  slow = snap["objectives"]["availability"]["slow"]
  assert slow["requests"] == 11 and slow["bad"] == 3
  # Completed requests carry an end-to-end latency sample too.
  assert snap["objectives"]["latency"]["slow"]["requests"] == 8


def test_router_stats_slo_block_carries_the_router_stream():
  transport = FakeTransport()

  def minimal(method, path, body, headers):
    if path == "/render":
      return _good_render("s")
    return 200, {}, b"{}"

  transport.set("hostA:1", minimal)
  transport.set("hostB:1", minimal)
  router = _two_backend_router(transport)
  sid, body = _scene_with_primary(router, "a")
  router.forward_render(sid, body)
  slo = router.stats()["slo"]
  assert slo["router"]["objectives"]["availability"]["slow"]["requests"] == 1
  # The fleet summary distilled from the backends still sits beside it.
  assert "attainment" in slo and "backends_reporting" in slo


def test_router_forwards_if_none_match_and_edge_headers():
  """The router is a pure conditional-request conduit: the client's
  If-None-Match reaches the backend, and the backend's ETag /
  Cache-Control / X-Edge-Cache ride back through the HTTP front end's
  forwarded headers (a 304 is an answered status, not a failure)."""
  transport = FakeTransport()
  seen = {}

  def edge_backend(method, path, body, headers):
    seen.update(headers)
    if headers.get("If-None-Match") == '"tag123"':
      return 304, {"ETag": '"tag123"', "Cache-Control": "max-age=5",
                   "X-Edge-Cache": "revalidated"}, b""
    return _good_render("s")

  transport.set("hostA:1", edge_backend)
  transport.set("hostB:1", edge_backend)
  router = _two_backend_router(transport)
  sid, body = _scene_with_primary(router, "a")
  status, headers, resp_body = router.forward_render(
      sid, body, if_none_match='"tag123"')
  assert seen.get("If-None-Match") == '"tag123"'
  assert status == 304 and resp_body == b""
  assert headers["ETag"] == '"tag123"'
  # The 304 counted as a healthy answer: breaker closed, SLO good.
  assert router.breaker_state("a") == "closed"
  assert router.slo.snapshot()[
      "objectives"]["availability"]["slow"]["bad"] == 0


# --- concurrent fan-out (a slow backend must not stall the scrape) -------


def test_router_fan_out_probes_backends_concurrently():
  """Both backends block on one barrier that only releases when BOTH
  probes are in flight at once — a serial fan-out would deadlock the
  first probe until its timeout. Deterministic: no sleeps, no timing."""
  barrier = threading.Barrier(2, timeout=10.0)

  def blocking_backend(method, path, body, headers):
    barrier.wait()  # serial fan-out: BrokenBarrierError after 10 s
    return 200, {}, json.dumps({"status": "ok"}).encode()

  transport = FakeTransport()
  transport.set("hostA:1", blocking_backend)
  transport.set("hostB:1", blocking_backend)
  router = _two_backend_router(transport)
  health = router.healthz()
  assert health["backends"] == {"a": "ok", "b": "ok"}
  assert health["status"] == "ok"


# --- the router's own HTTP front end ------------------------------------


@pytest.fixture
def http_router():
  """A socketed router front end over fake backends: hostA answers
  garbage 200s, hostB is dead — the 502-never-500 worst case."""
  transport = FakeTransport()
  transport.set("hostA:1",
                lambda m, p, b, h: (200, {"Content-Type":
                                          "application/json"}, b"garbage"))
  transport.set("hostB:1", _dead)
  router = _two_backend_router(transport, tracer=Tracer())
  server = make_router_http_server(router)
  thread = threading.Thread(target=server.serve_forever, daemon=True)
  thread.start()
  base = f"http://127.0.0.1:{server.server_address[1]}"
  yield base, router, transport
  server.shutdown()


def _post(base, payload, raw=None):
  data = raw if raw is not None else json.dumps(payload).encode()
  req = urllib.request.Request(base + "/render", data=data,
                               headers={"Content-Type": "application/json"})
  try:
    with urllib.request.urlopen(req, timeout=30) as resp:
      return resp.status, dict(resp.headers.items()), resp.read()
  except urllib.error.HTTPError as e:
    with e:
      return e.code, dict(e.headers.items()), e.read()


def test_http_router_garbage_backends_answer_502_never_500(http_router):
  base, _, _ = http_router
  status, headers, body = _post(
      base, {"scene_id": "scene_000", "pose": np.eye(4).tolist()})
  assert status == 502
  payload = json.loads(body)
  assert "attempts" in payload and len(payload["attempts"]) >= 1
  assert headers.get("X-Trace-Id")


def test_http_router_rejects_malformed_requests_with_400(http_router):
  base, _, _ = http_router
  assert _post(base, None, raw=b"{nope")[0] == 400
  assert _post(base, {"scene_id": ["not", "a", "string"],
                      "pose": np.eye(4).tolist()})[0] == 400
  assert _post(base, ["not", "an", "object"])[0] == 400


def test_http_router_open_breakers_answer_503_with_retry_after(http_router):
  base, router, _ = http_router
  for _ in range(2):  # open both breakers (threshold 2, both backends bad)
    _post(base, {"scene_id": "scene_000", "pose": np.eye(4).tolist()})
  status, headers, _ = _post(
      base, {"scene_id": "scene_000", "pose": np.eye(4).tolist()})
  assert status == 503 and int(headers["Retry-After"]) >= 1


# --- aggregated observability over fakes --------------------------------


def _obs_backend(metrics_text, health_status="ok"):
  def handler(method, path, body, headers):
    if path == "/healthz":
      return 200, {}, json.dumps({"status": health_status}).encode()
    if path == "/stats":
      return 200, {}, json.dumps({"requests": 1}).encode()
    if path.startswith("/metrics"):  # the router scrapes ?exemplars=1
      return 200, {}, metrics_text.encode()
    return 404, {}, b"{}"
  return handler


_EXPO_A = """# HELP mpi_serve_requests_total Completed render requests.
# TYPE mpi_serve_requests_total counter
mpi_serve_requests_total 3
# HELP mpi_serve_errors_total Failed requests by class.
# TYPE mpi_serve_errors_total counter
mpi_serve_errors_total{class="transient"} 1
"""

_EXPO_B = """# HELP mpi_serve_requests_total Completed render requests.
# TYPE mpi_serve_requests_total counter
mpi_serve_requests_total 5
# HELP mpi_serve_errors_total Failed requests by class.
# TYPE mpi_serve_errors_total counter
mpi_serve_errors_total{class="transient"} 2
"""


def test_aggregated_healthz_degraded_not_unhealthy_with_one_dead():
  transport = FakeTransport()
  transport.set("hostA:1", _obs_backend(_EXPO_A))
  transport.set("hostB:1", _dead)
  router = _two_backend_router(transport)
  health = router.healthz()
  assert health["status"] == "degraded"  # NOT unhealthy: a is serving
  assert health["backends"] == {"a": "ok", "b": "unreachable"}
  assert health["backends_reachable"] == 1
  assert "replicas cover" in health["reason"]


def test_aggregated_healthz_unhealthy_only_when_nobody_answers():
  transport = FakeTransport()
  transport.set("hostA:1", _dead)
  transport.set("hostB:1", _dead)
  router = _two_backend_router(transport)
  assert router.healthz()["status"] == "unhealthy"
  ok = FakeTransport()
  ok.set("hostA:1", _obs_backend(_EXPO_A))
  ok.set("hostB:1", _obs_backend(_EXPO_B))
  assert _two_backend_router(ok).healthz()["status"] == "ok"


def test_aggregated_metrics_sums_backends_and_adds_cluster_families():
  transport = FakeTransport()
  transport.set("hostA:1", _obs_backend(_EXPO_A))
  transport.set("hostB:1", _obs_backend(_EXPO_B))
  router = _two_backend_router(transport)
  families = parse_metrics_text(router.metrics_text())
  assert families["mpi_serve_requests_total"]["samples"][
      ("mpi_serve_requests_total", ())] == 8  # 3 + 5
  assert families["mpi_serve_errors_total"]["samples"][
      ("mpi_serve_errors_total", (("class", "transient"),))] == 3
  assert families["mpi_cluster_backends"]["samples"][
      ("mpi_cluster_backends", ())] == 2
  up = families["mpi_cluster_backend_up"]["samples"]
  assert up[("mpi_cluster_backend_up", (("backend", "a"),))] == 1
  assert up[("mpi_cluster_backend_up", (("backend", "b"),))] == 1


def test_aggregated_metrics_cached_for_ttl_under_injectable_clock():
  clock = FakeClock()
  transport = FakeTransport()
  transport.set("hostA:1", _obs_backend(_EXPO_A))
  transport.set("hostB:1", _obs_backend(_EXPO_B))
  router = _two_backend_router(transport, clock=clock)
  first = router.metrics_text()
  fanouts = len(transport.calls)
  # Inside the TTL: the STALE string comes back with zero fan-out.
  transport.set("hostA:1", _obs_backend(_EXPO_B))
  clock.t += 0.24
  assert router.metrics_text() == first
  assert len(transport.calls) == fanouts
  # Past the TTL: one fresh fan-out, new numbers (5 + 5).
  clock.t += 0.02
  families = parse_metrics_text(router.metrics_text())
  assert families["mpi_serve_requests_total"]["samples"][
      ("mpi_serve_requests_total", ())] == 10
  assert len(transport.calls) > fanouts


# --- the real thing: multi-process cluster on CPU -----------------------


@pytest.fixture(scope="module")
def cluster(healed_backends):
  """≥3 real serve processes + a router with per-backend breakers.

  The pool is the session-shared one (conftest.backend_pool) — spawning
  3 JAX processes is the expensive part, so every live suite rides the
  same fleet, re-gated healthy per module. The breaker cooldown is LONG
  so an opened breaker stays visibly open for the assertions; the
  resurrection test drives the probe through a fresh router with its
  own short-cooldown breakers.
  """
  pool, backends = healed_backends
  router = Router(backends, replication=2, breaker_threshold=2,
                  breaker_reset_s=600.0, render_timeout_s=120.0,
                  tracer=Tracer())
  yield pool, router


def _render_body(sid, tx=0.0):
  pose = np.eye(4)
  pose[0, 3] = tx
  return json.dumps({"scene_id": sid, "pose": pose.tolist()}).encode()


def _decode(body):
  payload = json.loads(body)
  img = np.frombuffer(base64.b64decode(payload["image_b64"]), "<f4")
  return img.reshape(payload["shape"])


def test_cluster_shards_scenes_and_routes_bit_identically(cluster):
  pool, router = cluster
  sids = pool.scene_ids()
  assert len(sids) >= 6
  primaries = {router.placement(sid)[0] for sid in sids}
  assert len(primaries) >= 2  # really sharded, not one hot backend
  for sid in sids[:3]:
    status, headers, body = router.forward_render(sid, _render_body(sid))
    assert status == 200
    routed = _decode(body)
    assert routed.shape == (pool.img_size, pool.img_size, 3)
    # Bit-identical to a DIRECT render on the very backend that served
    # it (the router is a pure forwarder; placement changes nothing in
    # the pixels).
    backend_addr = pool.addresses()[headers["X-Backend-Id"]]
    req = urllib.request.Request(
        f"http://{backend_addr}/render", data=_render_body(sid),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as resp:
      direct = _decode(resp.read())
    np.testing.assert_array_equal(routed, direct)


def test_cluster_trace_stitches_router_to_backend(cluster):
  pool, router = cluster
  sid = pool.scene_ids()[0]
  trace_id = "f" * 31 + "e"  # a fixed, greppable 32-hex id
  tr = router.tracer.start_trace("route", trace_id=trace_id, scene_id=sid)
  status, headers, _ = router.forward_render(
      sid, _render_body(sid, tx=0.01), trace_id=trace_id, trace=tr)
  tr.finish()
  assert status == 200
  # The backend honored the outbound traceparent: ITS response header
  # carries OUR trace id...
  assert headers["X-Trace-Id"] == trace_id
  backend_addr = pool.addresses()[headers["X-Backend-Id"]]
  with urllib.request.urlopen(
      f"http://{backend_addr}/debug/traces", timeout=30) as resp:
    backend_traces = json.loads(resp.read())
  backend_ids = {t["trace_id"] for t in backend_traces["recent"]}
  # ...and recorded a span tree under it, as did the router: one id,
  # two processes, a stitched distributed trace.
  assert trace_id in backend_ids
  router_ids = {t["trace_id"] for t in router.tracer.snapshot()["recent"]}
  assert trace_id in router_ids
  backend_tr = next(t for t in backend_traces["recent"]
                    if t["trace_id"] == trace_id)
  assert {"queue_wait", "dispatch"} <= {s["name"]
                                        for s in backend_tr["spans"]}


def test_cluster_sigkill_mid_load_fails_over_and_isolates(cluster):
  pool, router = cluster
  sids = pool.scene_ids()
  victim = router.placement(sids[0])[0]
  victim_scenes = [s for s in sids if victim in router.placement(s)]
  assert victim_scenes  # the victim must actually matter

  stop = threading.Event()
  failures: list[str] = []
  post_kill_ok: set[str] = set()
  killed = threading.Event()
  lock = threading.Lock()

  def worker(widx):
    i = 0
    while not stop.is_set():
      sid = sids[(widx + i) % len(sids)]
      i += 1
      try:
        status, _, _ = router.forward_render(
            sid, _render_body(sid, tx=0.002 * (i % 5)))
      except Exception as e:  # noqa: BLE001 - transition failures expected
        with lock:
          failures.append(f"{sid}: {e!r}")
        continue
      if status == 200 and killed.is_set():
        with lock:
          post_kill_ok.add(sid)

  threads = [threading.Thread(target=worker, args=(w,), daemon=True)
             for w in range(3)]
  for t in threads:
    t.start()
  # Let the load establish, then SIGKILL one backend under it.
  deadline = 60.0
  import time as _time
  t0 = _time.monotonic()
  while not router.metrics.snapshot()["requests"] and \
      _time.monotonic() - t0 < deadline:
    _time.sleep(0.05)
  pool.kill(victim)
  killed.set()
  # Keep loading until EVERY scene the victim served has rendered
  # successfully post-kill (failover proven), or the deadline says no.
  while not set(victim_scenes) <= post_kill_ok and \
      _time.monotonic() - t0 < deadline:
    _time.sleep(0.1)
  stop.set()
  for t in threads:
    t.join(30)

  assert set(victim_scenes) <= post_kill_ok, (
      f"scenes never failed over: {set(victim_scenes) - post_kill_ok}; "
      f"failures={failures[:5]}")
  info = router.stats()["backend_info"]
  assert info[victim]["breaker"]["state"] == "open"
  for bid, binfo in info.items():
    if bid != victim:
      assert binfo["breaker"]["state"] == "closed", (
          f"healthy backend {bid} breaker opened: {binfo}")  # isolation
  health = router.healthz()
  assert health["status"] == "degraded"  # NOT unhealthy: replicas cover
  assert health["backends_reachable"] == pool.n_backends - 1
  assert router.metrics.snapshot()["failovers"] >= 1


def test_cluster_resurrected_backend_serves_again(cluster):
  """The dead backend restarts on its old port; a fresh router (short
  breaker cooldown) sees its breaker open, then re-close through the
  half-open probe, then traffic flows to it again."""
  pool, router = cluster
  sids = pool.scene_ids()
  victim = router.placement(sids[0])[0]
  if pool.alive(victim):  # runs after the SIGKILL test; be self-sufficient
    pool.kill(victim)
  probe_router = Router(pool.addresses(), replication=2,
                        breaker_threshold=1, breaker_reset_s=0.5,
                        render_timeout_s=120.0)
  sid = next(s for s in sids if probe_router.placement(s)[0] == victim)
  status, headers, _ = probe_router.forward_render(sid, _render_body(sid))
  assert status == 200 and headers["X-Backend-Id"] != victim  # failover
  assert probe_router.stats()["backend_info"][victim]["breaker"][
      "state"] == "open"
  pool.restart(victim)
  import time as _time
  deadline = _time.monotonic() + 30.0
  served_by = None
  while _time.monotonic() < deadline:
    status, headers, _ = probe_router.forward_render(sid, _render_body(sid))
    assert status == 200
    if headers["X-Backend-Id"] == victim:
      served_by = victim
      break
    _time.sleep(0.2)
  assert served_by == victim, "probe never re-closed the breaker"
  assert probe_router.stats()["backend_info"][victim]["breaker"][
      "state"] == "closed"
