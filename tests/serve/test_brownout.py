"""Brownout: the degradation ladder, priority admission, and the two
contracts that make it safe to run.

Three layers, cheapest first:

  * Ladder state-machine tests on a fake clock — hysteresis
    (no-flapping band), one-level-at-a-time descent with dwell,
    fast-window recovery where every step earns its own healthy window,
    priority shed ordering (interactive last), max_level cap.
  * In-process service pins — L0 bit-exactness vs a brownout-less
    service, degraded renders labelled and full-shape, the cache
    contract (degraded frames never populate the edge cache and never
    carry an ETag; L3 widens warp tolerance over full-quality entries
    only), the recovery contract (sheds count in brownout families,
    never in SLO bad), and the HTTP header surface.
  * Router aggregation over fake transports — class forwarding,
    degraded-header passthrough, the fleet brownout summary, and the
    asset-304 answered at the router without waking a backend.
"""

import base64
import json
import random
import threading
import types
import urllib.error
import urllib.request

import numpy as np
import pytest

from mpi_vision_tpu.obs import SloConfig
from mpi_vision_tpu.serve import RenderService, make_http_server
from mpi_vision_tpu.serve import brownout
from mpi_vision_tpu.serve.assets.fetch import SceneFetcher
from mpi_vision_tpu.serve.assets.store import asset_etag
from mpi_vision_tpu.serve.cluster import Router, make_router_http_server
from mpi_vision_tpu.serve.edge.cache import EdgeConfig
from mpi_vision_tpu.serve.resilience import RetryPolicy
from mpi_vision_tpu.serve.scheduler import QueueFullError

H = W = 16
P = 4


class FakeClock:
  def __init__(self, t=100.0):
    self.t = t

  def __call__(self):
    return self.t


def _pose(tx=0.0):
  pose = np.eye(4, dtype=np.float32)
  pose[0, 3] = tx
  return pose


def _ladder(burn=0.0, queue=0.0, clock=None, **cfg):
  """A controller on mutable signal holders and (optionally) a fake
  clock; eval rate limit off so every tick evaluates."""
  cfg.setdefault("eval_interval_s", 0.0)
  sig = {"burn": burn, "queue": queue}
  ctl = brownout.BrownoutController(
      brownout.BrownoutConfig(**cfg),
      burn_fn=lambda: sig["burn"], queue_fn=lambda: sig["queue"],
      clock=clock if clock is not None else FakeClock())
  return ctl, sig


# --- config & key helpers ------------------------------------------------


def test_config_rejects_inverted_hysteresis_band():
  with pytest.raises(ValueError, match="hysteresis"):
    brownout.BrownoutConfig(recover_burn=2.0, burn_high=2.0)
  with pytest.raises(ValueError, match="hysteresis"):
    brownout.BrownoutConfig(recover_queue=0.5, queue_high=0.5)
  with pytest.raises(ValueError, match="plane_keep"):
    brownout.BrownoutConfig(plane_keep=0.0)
  with pytest.raises(ValueError, match="l3_warp_scale"):
    brownout.BrownoutConfig(l3_warp_scale=0.5)
  with pytest.raises(ValueError, match="max_level"):
    brownout.BrownoutConfig(max_level=0)


def test_normalize_class_unknown_is_interactive():
  assert brownout.normalize_class(None) == "interactive"
  assert brownout.normalize_class(" Prefetch ") == "prefetch"
  assert brownout.normalize_class("vip") == "interactive"
  assert brownout.shed_level("background") == 2
  assert brownout.shed_level("interactive") == 4


def test_half_res_key_roundtrip():
  key = brownout.half_res_key("scene_000")
  assert key != "scene_000"
  assert brownout.split_degrade_key(key) == ("scene_000", True)
  assert brownout.split_degrade_key("scene_000") == ("scene_000", False)


# --- the ladder state machine (fake clock) -------------------------------


def test_first_descent_immediate_then_one_level_per_dwell():
  clk = FakeClock()
  ctl, sig = _ladder(burn=10.0, clock=clk, step_dwell_s=2.0,
                     recover_dwell_s=5.0)
  assert ctl.tick() == 1  # first response to an incident: immediate
  assert ctl.tick() == 1  # consecutive steps wait out the dwell
  clk.t += 1.9
  assert ctl.tick() == 1
  clk.t += 0.1
  assert ctl.tick() == 2
  clk.t += 2.0
  assert ctl.tick() == 3
  clk.t += 2.0
  assert ctl.tick() == 4
  clk.t += 10.0
  assert ctl.tick() == 4  # capped at max_level
  assert ctl.transitions_down == 4 and ctl.transitions_up == 0


def test_queue_signal_alone_drives_descent():
  clk = FakeClock()
  ctl, sig = _ladder(queue=0.9, clock=clk, step_dwell_s=0.0)
  assert ctl.tick() == 1
  sig["queue"] = 0.3  # inside the band (0.25, 0.5): hold
  clk.t += 100.0
  assert ctl.tick() == 1
  sig["queue"] = 0.1  # healthy
  clk.t += 1.0
  ctl.tick()  # healthy timer starts here
  clk.t += ctl.config.recover_dwell_s
  assert ctl.tick() == 0


def test_recovery_needs_a_full_healthy_window_per_step():
  clk = FakeClock()
  ctl, sig = _ladder(burn=10.0, clock=clk, step_dwell_s=0.0,
                     recover_dwell_s=5.0)
  ctl.tick()
  ctl.tick()
  assert ctl.level == 2
  sig["burn"] = 0.5  # healthy
  ctl.tick()  # healthy_since = now
  clk.t += 4.9
  assert ctl.tick() == 2  # 4.9 < 5: not yet
  clk.t += 0.1
  assert ctl.tick() == 1  # one step, and the timer restarts
  assert ctl.tick() == 1  # a 2-level climb is TWO sustained windows
  clk.t += 5.0
  assert ctl.tick() == 0
  assert ctl.transitions_up == 2


def test_hysteresis_band_resets_the_healthy_timer():
  clk = FakeClock()
  ctl, sig = _ladder(burn=10.0, clock=clk, step_dwell_s=0.0,
                     recover_dwell_s=5.0)
  assert ctl.tick() == 1
  sig["burn"] = 0.5
  ctl.tick()
  clk.t += 4.9  # almost recovered...
  assert ctl.tick() == 1
  sig["burn"] = 1.5  # ...then a blip into the band (1.0, 2.0)
  clk.t += 0.1
  assert ctl.tick() == 1  # held, not descended (band != overload)
  sig["burn"] = 0.5
  clk.t += 0.1
  ctl.tick()  # the blip reset the timer: a fresh full window is owed
  clk.t += 4.9
  assert ctl.tick() == 1
  clk.t += 0.1
  assert ctl.tick() == 0
  assert ctl.transitions_down == 1 and ctl.transitions_up == 1


def test_priority_shed_ordering_interactive_last():
  clk = FakeClock()
  ctl, sig = _ladder(burn=10.0, clock=clk, step_dwell_s=0.0,
                     recover_dwell_s=3600.0, shed_retry_after_s=2.5)
  for want_level, shed, admitted in (
      (1, (), ("interactive", "prefetch", "background")),
      (2, ("background",), ("interactive", "prefetch")),
      (3, ("background", "prefetch"), ("interactive",)),
      (4, ("background", "prefetch", "interactive"), ()),
  ):
    sig["burn"] = 10.0
    ctl.tick()
    sig["burn"] = 1.5  # hold in the band while we probe admission
    assert ctl.level == want_level
    for cls in admitted:
      assert ctl.admit(cls) == want_level
    for cls in shed:
      with pytest.raises(brownout.BrownoutShedError) as err:
        ctl.admit(cls)
      assert err.value.request_class == cls
      assert err.value.level == want_level
      assert err.value.retry_after_s == 2.5
      assert isinstance(err.value, QueueFullError)  # rides the 503 arm


def test_max_level_cap_holds_the_ladder_down():
  clk = FakeClock()
  ctl, _ = _ladder(burn=10.0, clock=clk, step_dwell_s=0.0, max_level=2)
  for _ in range(5):
    ctl.tick()
  assert ctl.level == 2
  ctl.admit("interactive")  # interactive sheds only at 4: still served


def test_snapshot_and_reset_counters():
  ctl, sig = _ladder(burn=10.0, step_dwell_s=0.0)
  ctl.tick()
  snap = ctl.snapshot()
  assert snap["enabled"] is True and snap["level"] == 1
  assert snap["transitions"] == {"down": 1, "up": 0}
  assert snap["signals"]["burn"] == 10.0
  ctl.reset_counters()
  assert ctl.snapshot()["transitions"] == {"down": 0, "up": 0}
  assert ctl.level == 1  # the level is live state, not a counter


# --- in-process service pins ---------------------------------------------


@pytest.fixture(scope="module")
def svc_bo():
  svc = RenderService(max_batch=2, max_wait_ms=1.0, use_mesh=False,
                      method="fused", slo=SloConfig(),
                      brownout=brownout.BrownoutConfig())
  svc.add_synthetic_scenes(1, height=H, width=W, planes=P)
  yield svc
  svc.close()


@pytest.fixture(scope="module")
def svc_plain():
  svc = RenderService(max_batch=2, max_wait_ms=1.0, use_mesh=False,
                      method="fused")
  svc.add_synthetic_scenes(1, height=H, width=W, planes=P)
  yield svc
  svc.close()


def _arm(svc, level):
  """Pin the service's ladder at ``level`` via injected signals: climb
  on a saturated burn, then hold in the hysteresis band."""
  sig = {"burn": 10.0}
  ctl = brownout.BrownoutController(
      brownout.BrownoutConfig(step_dwell_s=0.0, recover_dwell_s=3600.0,
                              eval_interval_s=0.0),
      burn_fn=lambda: sig["burn"], queue_fn=lambda: 0.0)
  for _ in range(level):
    ctl.tick()
  sig["burn"] = 1.5
  assert ctl.level == level
  svc.brownout = ctl
  return ctl


def test_l0_bit_identical_to_a_service_without_brownout(svc_bo, svc_plain):
  _arm(svc_bo, 0)
  pose = _pose(0.01)
  img, info = svc_bo.render_request("scene_000", pose,
                                    request_class="interactive")
  assert info["level"] == 0 and info["degraded"] is False
  np.testing.assert_array_equal(img, svc_plain.render("scene_000", pose))


def test_l2_render_full_shape_degraded_and_counted(svc_bo):
  _arm(svc_bo, 0)
  pose = _pose(0.02)
  full, _ = svc_bo.render_request("scene_000", pose)
  _arm(svc_bo, 2)
  img, info = svc_bo.render_request("scene_000", pose,
                                    request_class="interactive")
  assert img.shape == (H, W, 3)  # upsampled back to the request raster
  assert info["level"] == 2 and info["degraded"] is True
  assert not np.array_equal(img, full)  # genuinely lower fidelity
  snap = svc_bo.metrics.snapshot()
  assert snap["brownout"]["degraded"]["2"] >= 1


def test_degrade_batch_keys_never_coalesce(svc_bo):
  pose = _pose()
  k0, _ = svc_bo._tile_batch_key("scene_000", pose, degrade=0)
  k2, _ = svc_bo._tile_batch_key("scene_000", pose, degrade=2)
  assert k0 != k2
  assert brownout.split_degrade_key(k2) == (k0, True)


def test_shed_counts_in_brownout_families_never_slo_bad(svc_bo):
  _arm(svc_bo, 4)
  bad_before = svc_bo.slo.snapshot()[
      "objectives"]["availability"]["slow"]["bad"]
  sheds_before = svc_bo.metrics.snapshot()["brownout"]["sheds"]
  with pytest.raises(brownout.BrownoutShedError) as err:
    svc_bo.render_request("scene_000", _pose(), request_class="prefetch")
  assert err.value.level == 4 and err.value.retry_after_s > 0
  snap = svc_bo.metrics.snapshot()["brownout"]["sheds"]
  assert snap["prefetch"] == sheds_before["prefetch"] + 1
  # The recovery contract: a shed is load management, not an outage.
  assert svc_bo.slo.snapshot()[
      "objectives"]["availability"]["slow"]["bad"] == bad_before


def test_stats_overlays_controller_state(svc_bo):
  _arm(svc_bo, 3)
  block = svc_bo.stats()["brownout"]
  assert block["enabled"] is True and block["level"] == 3
  assert "sheds" in block and "signals" in block


# --- HTTP header surface -------------------------------------------------


@pytest.fixture(scope="module")
def http_bo(svc_bo):
  httpd = make_http_server(svc_bo, port=0)
  thread = threading.Thread(target=httpd.serve_forever, daemon=True)
  thread.start()
  yield f"http://127.0.0.1:{httpd.server_address[1]}"
  httpd.shutdown()


def _post_render(base, request_class=None, tx=0.0):
  body = json.dumps({"scene_id": "scene_000",
                     "pose": _pose(tx).tolist()}).encode()
  headers = {"Content-Type": "application/json"}
  if request_class is not None:
    headers[brownout.REQUEST_CLASS_HEADER] = request_class
  req = urllib.request.Request(base + "/render", data=body, headers=headers)
  try:
    with urllib.request.urlopen(req, timeout=60) as resp:
      return resp.status, dict(resp.headers.items())
  except urllib.error.HTTPError as e:
    with e:
      return e.code, dict(e.headers.items())


def test_http_degraded_response_is_labelled_and_uncacheable(svc_bo, http_bo):
  _arm(svc_bo, 2)
  status, headers = _post_render(http_bo, request_class="interactive")
  assert status == 200
  assert headers[brownout.LEVEL_HEADER] == "2"
  assert headers[brownout.DEGRADED_HEADER] == "1"
  assert headers["Cache-Control"] == "no-store"
  assert "ETag" not in headers


def test_http_shed_is_503_with_retry_after_and_level(svc_bo, http_bo):
  _arm(svc_bo, 2)
  status, headers = _post_render(http_bo, request_class="background")
  assert status == 503
  assert float(headers["Retry-After"]) > 0
  assert headers[brownout.LEVEL_HEADER] == "2"


def test_http_l0_carries_level_zero_and_no_degraded_marker(svc_bo, http_bo):
  _arm(svc_bo, 0)
  status, headers = _post_render(http_bo, request_class="interactive")
  assert status == 200
  assert headers[brownout.LEVEL_HEADER] == "0"
  assert brownout.DEGRADED_HEADER not in headers


# --- the edge-cache contract ---------------------------------------------


@pytest.fixture
def svc_edge():
  svc = RenderService(
      max_batch=2, max_wait_ms=1.0, use_mesh=False, method="fused",
      slo=SloConfig(),
      edge=EdgeConfig(trans_cell=0.01, rot_bucket_deg=90.0,
                      warp_max_trans=0.02, warp_max_rot_deg=45.0),
      brownout=brownout.BrownoutConfig())
  svc.add_synthetic_scenes(1, height=H, width=W, planes=P)
  yield svc
  svc.close()


def test_degraded_frames_never_enter_the_edge_cache(svc_edge):
  _arm(svc_edge, 2)
  img, info = svc_edge.render_request("scene_000", _pose())
  assert info["edge"] == "miss" and info["degraded"] is True
  assert info["etag"] is None
  assert svc_edge.edge.stats()["frames"] == 0  # the cell stayed empty
  # A full-quality render fills the cell and earns the strong ETag...
  _arm(svc_edge, 0)
  img0, info0 = svc_edge.render_request("scene_000", _pose())
  assert info0["edge"] == "miss" and info0["etag"]
  assert svc_edge.edge.stats()["frames"] == 1
  # ...and only THAT entry serves hits, at full quality.
  img1, info1 = svc_edge.render_request("scene_000", _pose())
  assert info1["edge"] == "hit" and info1["degraded"] is False
  assert info1["etag"] == info0["etag"]
  np.testing.assert_array_equal(img1, img0)


def test_l3_widens_warp_tolerance_and_labels_the_serve(svc_edge):
  _arm(svc_edge, 0)
  _, info0 = svc_edge.render_request("scene_000", _pose())
  assert info0["edge"] == "miss" and info0["etag"]
  # 0.04 translation: outside the base warp tolerance (0.02), inside
  # the L3-widened one (3x = 0.06).
  _arm(svc_edge, 3)
  img, info = svc_edge.render_request("scene_000", _pose(0.04),
                                      request_class="interactive")
  assert info["edge"] == "warp"
  assert info["degraded"] is True  # served only because L3 widened it
  assert info["etag"] is None  # pose-specific warp: never validatable
  assert svc_edge.metrics.snapshot()["brownout"]["degraded"]["3"] >= 1
  # The same request at L0 would NOT warp-serve: it renders.
  _arm(svc_edge, 0)
  _, info_l0 = svc_edge.render_request("scene_000", _pose(0.04))
  assert info_l0["edge"] == "miss" and info_l0["degraded"] is False


# --- router: forwarding, aggregation, asset 304 --------------------------


class FakeTransport:
  def __init__(self):
    self.handlers = {}
    self.calls = []

  def set(self, address, handler):
    self.handlers[address] = handler

  def request(self, method, url, body=None, headers=None, timeout=30.0):
    address, _, path = url[len("http://"):].partition("/")
    self.calls.append((address, method, "/" + path))
    return self.handlers[address](method, "/" + path, body, headers or {})


def _router(transport):
  return Router({"a": "hostA:1", "b": "hostB:1"}, replication=2,
                breaker_threshold=2, breaker_reset_s=10.0,
                transport=transport, clock=FakeClock())


def test_router_brownout_summary_pools_the_fleet():
  per = {
      "a": {"brownout": {"enabled": True, "level": 2,
                         "sheds": {"background": 3},
                         "degraded": {"2": 5}}},
      "b": {"brownout": {"enabled": True, "level": 0,
                         "sheds": {"background": 1, "prefetch": 2},
                         "degraded": {}}},
      "c": {"brownout": {"enabled": False, "level": 0,
                         "sheds": {}, "degraded": {}}},
      "d": {"error": "unreachable"},
  }
  out = Router._brownout_summary(per)
  assert out == {
      "backends_reporting": 3,
      "backends_enabled": 2,
      "max_level": 2,
      "levels": {"a": 2},
      "sheds": {"background": 4, "prefetch": 2},
      "degraded_total": 5,
  }


def test_router_stats_carry_the_fleet_brownout_block():
  def backend(method, path, body, headers):
    if path == "/stats":
      return 200, {}, json.dumps({
          "brownout": {"enabled": True, "level": 1,
                       "sheds": {"background": 2}, "degraded": {"1": 1}},
      }).encode()
    return 200, {}, json.dumps({}).encode()

  transport = FakeTransport()
  transport.set("hostA:1", backend)
  transport.set("hostB:1", backend)
  out = _router(transport).stats()["brownout"]
  assert out["backends_enabled"] == 2 and out["max_level"] == 1
  assert out["sheds"] == {"background": 4}


@pytest.fixture
def http_router_bo():
  """A socketed router over fake backends that echo brownout headers
  and record what the router forwarded to them."""
  seen = {}

  def backend(method, path, body, headers):
    seen.update(headers)
    if method == "GET":
      return 200, {"Content-Type": "application/octet-stream",
                   "ETag": asset_etag("ab" * 32)}, b"asset-bytes"
    # A structurally valid render body — the router validates 200s
    # before forwarding them (1x1x3 float32 => 12 bytes => 16 b64).
    pixels = base64.b64encode(np.zeros((1, 1, 3), np.float32).tobytes())
    return 200, {"Content-Type": "application/json",
                 brownout.LEVEL_HEADER: "2",
                 brownout.DEGRADED_HEADER: "1",
                 "Cache-Control": "no-store"}, json.dumps(
                     {"scene_id": "s1", "shape": [1, 1, 3],
                      "image_b64": pixels.decode()}).encode()

  transport = FakeTransport()
  transport.set("hostA:1", backend)
  transport.set("hostB:1", backend)
  router = _router(transport)
  server = make_router_http_server(router)
  thread = threading.Thread(target=server.serve_forever, daemon=True)
  thread.start()
  base = f"http://127.0.0.1:{server.server_address[1]}"
  yield base, router, transport, seen
  server.shutdown()


def test_http_router_forwards_class_and_degraded_headers(http_router_bo):
  base, _, _, seen = http_router_bo
  body = json.dumps({"scene_id": "s1",
                     "pose": np.eye(4).tolist()}).encode()
  req = urllib.request.Request(
      base + "/render", data=body,
      headers={"Content-Type": "application/json",
               brownout.REQUEST_CLASS_HEADER: "prefetch"})
  with urllib.request.urlopen(req, timeout=30) as resp:
    headers = dict(resp.headers.items())
  assert seen.get(brownout.REQUEST_CLASS_HEADER) == "prefetch"
  assert headers[brownout.LEVEL_HEADER] == "2"
  assert headers[brownout.DEGRADED_HEADER] == "1"
  assert headers["Cache-Control"] == "no-store"


def test_http_router_answers_asset_304_without_a_backend(http_router_bo):
  base, router, transport, _ = http_router_bo
  digest = "ab" * 32
  etag = asset_etag(digest)
  calls_before = len(transport.calls)
  req = urllib.request.Request(
      base + f"/scene/s1/asset/{digest}",
      headers={"If-None-Match": etag})
  with pytest.raises(urllib.error.HTTPError) as err:
    urllib.request.urlopen(req, timeout=30)
  with err.value:
    assert err.value.code == 304
    assert err.value.headers["ETag"] == etag
    assert "immutable" in err.value.headers["Cache-Control"]
  # Proven fresh by arithmetic: no backend was consulted.
  assert len(transport.calls) == calls_before
  assert router.metrics.snapshot()["scene_sync"]["asset_revalidations"] == 1
  # Without the matching validator the GET forwards as before.
  with urllib.request.urlopen(base + f"/scene/s1/asset/{digest}",
                              timeout=30) as resp:
    assert resp.status == 200 and resp.read() == b"asset-bytes"
  assert len(transport.calls) > calls_before


# --- scene fetcher: transient retry + background class -------------------


class FlakyFetchTransport:
  def __init__(self, failures):
    self.failures = failures
    self.calls = 0
    self.headers_seen = []

  def get(self, url, headers=None):
    self.calls += 1
    self.headers_seen.append(dict(headers or {}))
    if self.calls <= self.failures:
      raise ConnectionError("connection reset")
    return 200, {}, json.dumps({"scenes": ["s1"]}).encode()


def _fetch_service():
  retries = {"n": 0}
  metrics = types.SimpleNamespace(
      record_scene_sync_retry=lambda: retries.__setitem__(
          "n", retries["n"] + 1))
  return types.SimpleNamespace(metrics=metrics, events=None), retries


def test_fetcher_retries_transient_failures_with_backoff():
  transport = FlakyFetchTransport(failures=2)
  service, retries = _fetch_service()
  sleeps = []
  fetcher = SceneFetcher(
      service, "http://upstream", transport=transport,
      retry=RetryPolicy(max_retries=2, backoff_base_s=0.05,
                        backoff_mult=2.0, jitter=0.1),
      sleep=sleeps.append, rng=random.Random(0))
  assert fetcher.remote_scenes() == ["s1"]
  assert transport.calls == 3 and retries["n"] == 2
  assert len(sleeps) == 2
  assert 0.05 * 0.9 <= sleeps[0] <= 0.05 * 1.1  # base +- jitter
  assert 0.10 * 0.9 <= sleeps[1] <= 0.10 * 1.1  # exponential
  # Every attempt declares itself background traffic: a browned-out
  # upstream sheds the sync sweep before any interactive render.
  for headers in transport.headers_seen:
    assert headers[brownout.REQUEST_CLASS_HEADER] == "background"


def test_fetcher_retry_budget_exhausts_to_the_caller():
  transport = FlakyFetchTransport(failures=10)
  service, retries = _fetch_service()
  fetcher = SceneFetcher(
      service, "http://upstream", transport=transport,
      retry=RetryPolicy(max_retries=2), sleep=lambda s: None,
      rng=random.Random(0))
  with pytest.raises(ConnectionError):
    fetcher.remote_scenes()
  assert transport.calls == 3  # 1 + max_retries, then give up
  assert retries["n"] == 2
