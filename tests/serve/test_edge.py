"""Edge frame cache (serve/edge/): lattice, cache, warp, HTTP semantics.

The acceptance pins from the edge-cache issue live here: (1) an
exact-cell hit serves bytes bit-identical to the cell's first real
render; (2) a near-miss is served by warping a cached frame only when
the pose error is under the configured thresholds; (3) ``swap_scenes``
invalidates cached frames — no frame of the old pixels survives a live
reload, and the post-swap response is bit-identical to a fresh render;
(4) strong-ETag revalidation answers 304 over real HTTP and stops
matching after a swap.

Scenes stay at the suite's shared 16x16x4 shape so the XLA compiles are
reused from the other serve tests.
"""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from mpi_vision_tpu.serve import RenderService, make_http_server
from mpi_vision_tpu.serve.edge import (
    EdgeConfig,
    EdgeFrameCache,
    pose_error,
    quantize_pose,
    warp_frame,
)
from mpi_vision_tpu.serve.server import synthetic_scene

H = W = 16
P = 4


def _pose(tx=0.0, ty=0.0, tz=0.0, yaw_deg=0.0):
  pose = np.eye(4, dtype=np.float32)
  if yaw_deg:
    a = np.radians(yaw_deg)
    pose[0, 0] = pose[2, 2] = np.cos(a)
    pose[0, 2], pose[2, 0] = np.sin(a), -np.sin(a)
  pose[:3, 3] = (tx, ty, tz)
  return pose


# --- lattice -------------------------------------------------------------


def test_quantize_pose_is_stable_within_a_cell():
  cell = quantize_pose(_pose(0.011, 0.0, 0.0), 0.01, 2.0)
  assert quantize_pose(_pose(0.019, 0.0, 0.0), 0.01, 2.0) == cell
  assert quantize_pose(_pose(0.021, 0.0, 0.0), 0.01, 2.0) != cell
  assert quantize_pose(_pose(0.011, yaw_deg=3.0), 0.01, 2.0) != cell
  # Rotations inside one bucket share the cell.
  assert (quantize_pose(_pose(yaw_deg=0.5), 0.01, 2.0)
          == quantize_pose(_pose(yaw_deg=1.4), 0.01, 2.0))


def test_pose_error_translation_and_rotation():
  trans, rot = pose_error(_pose(0.03), _pose(0.0))
  assert trans == pytest.approx(0.03, abs=1e-6)
  assert rot == pytest.approx(0.0, abs=1e-4)
  trans, rot = pose_error(_pose(yaw_deg=5.0), _pose())
  assert trans == pytest.approx(0.0, abs=1e-6)
  assert rot == pytest.approx(5.0, abs=1e-3)


# --- cache ---------------------------------------------------------------


def _frame(fill=0.5, h=4, w=4):
  return np.full((h, w, 3), fill, np.float32)


def _cache(**overrides):
  kwargs = dict(trans_cell=0.01, rot_bucket_deg=2.0, warp_max_trans=0.05,
                warp_max_rot_deg=4.0, byte_budget=1 << 20)
  kwargs.update(overrides)
  return EdgeFrameCache(EdgeConfig(**kwargs))


def test_cache_hit_warp_miss_classification():
  cache = _cache()
  k = np.eye(3, dtype=np.float32)
  kind, entry, cell = cache.lookup("s", "d", _pose(0.001))
  assert kind == "miss" and entry is None
  put = cache.put("s", "d", cell, _pose(0.001), _frame(), k, 10.0)
  # Exact cell (different pose inside it) -> hit on the stored entry.
  kind, entry, _ = cache.lookup("s", "d", _pose(0.009))
  assert kind == "hit" and entry.etag == put.etag
  # Neighboring cell inside the warp thresholds -> warp off it.
  kind, entry, _ = cache.lookup("s", "d", _pose(0.03))
  assert kind == "warp" and entry.etag == put.etag
  # Beyond the warp radius -> miss.
  kind, entry, _ = cache.lookup("s", "d", _pose(0.2))
  assert kind == "miss" and entry is None
  # A different params digest never matches.
  kind, _, _ = cache.lookup("s", "other", _pose(0.001))
  assert kind == "miss"
  stats = cache.stats()
  assert (stats["hits"], stats["warp_serves"], stats["misses"]) == (1, 1, 3)
  assert stats["hit_rate"] == pytest.approx(0.4)


def test_cache_warp_picks_the_nearest_entry():
  cache = _cache()
  k = np.eye(3, dtype=np.float32)
  for tx in (0.0, 0.045):
    _, _, cell = cache.lookup("s", "d", _pose(tx))
    cache.put("s", "d", cell, _pose(tx), _frame(tx), k, 10.0)
  kind, entry, _ = cache.lookup("s", "d", _pose(0.035))
  assert kind == "warp"
  assert float(entry.pose[0, 3]) == pytest.approx(0.045)


def test_cache_put_is_first_writer_wins():
  cache = _cache()
  k = np.eye(3, dtype=np.float32)
  _, _, cell = cache.lookup("s", "d", _pose())
  first = cache.put("s", "d", cell, _pose(), _frame(0.1), k, 10.0)
  second = cache.put("s", "d", cell, _pose(0.004), _frame(0.9), k, 10.0)
  assert second is first  # the resident entry (and its ETag) stand


def test_cache_byte_budget_evicts_lru():
  one = _frame().nbytes
  cache = _cache(byte_budget=3 * one)  # ~2 entries + metadata
  k = np.eye(3, dtype=np.float32)
  cells = []
  for i, tx in enumerate((0.0, 0.1, 0.2)):
    _, _, cell = cache.lookup("s", "d", _pose(tx))
    cells.append(cell)
    cache.put("s", "d", cell, _pose(tx), _frame(i * 0.1), k, 10.0)
  stats = cache.stats()
  assert stats["evictions"] >= 1 and stats["bytes"] <= 3 * one
  # The oldest cell was the victim; the newest survives.
  with cache._lock:
    assert ("s", "d", cells[0]) not in cache._entries
    assert ("s", "d", cells[-1]) in cache._entries


def test_cache_invalidate_scene_drops_all_digests():
  cache = _cache()
  k = np.eye(3, dtype=np.float32)
  for digest in ("d1", "d2"):
    _, _, cell = cache.lookup("s", digest, _pose())
    cache.put("s", digest, cell, _pose(), _frame(), k, 10.0)
  _, _, cell = cache.lookup("other", "d1", _pose())
  cache.put("other", "d1", cell, _pose(), _frame(), k, 10.0)
  assert cache.invalidate_scene("s") == 2
  assert len(cache) == 1 and cache.stats()["invalidations"] == 2
  assert cache.lookup("s", "d1", _pose())[0] == "miss"
  assert cache.lookup("other", "d1", _pose())[0] == "hit"


def test_cache_revalidate_only_matches_resident_entries():
  cache = _cache()
  k = np.eye(3, dtype=np.float32)
  _, _, cell = cache.lookup("s", "d", _pose())
  entry = cache.put("s", "d", cell, _pose(), _frame(), k, 10.0)
  assert cache.revalidate("s", "d", _pose(0.004), entry.etag) == entry.etag
  assert cache.revalidate("s", "d", _pose(), '"bogus"') is None
  assert cache.revalidate("s", "d", _pose(), f'"bogus", {entry.etag}') \
      == entry.etag
  cache.invalidate_scene("s")
  assert cache.revalidate("s", "d", _pose(), entry.etag) is None
  assert cache.stats()["revalidations"] == 2


# --- warp ----------------------------------------------------------------


def test_warp_frame_identity_pose_is_near_exact():
  rng = np.random.default_rng(0)
  frame = rng.uniform(0, 1, (H, W, 3)).astype(np.float32)
  k = np.asarray([[8.0, 0, 8.0], [0, 8.0, 8.0], [0, 0, 1]], np.float32)
  out = warp_frame(frame, _pose(0.01), _pose(0.01), k, 10.0)
  np.testing.assert_allclose(out, frame, atol=1e-5)


# --- service integration -------------------------------------------------


@pytest.fixture(scope="module")
def svc():
  service = RenderService(
      max_batch=4, max_wait_ms=5.0, use_mesh=False,
      edge=EdgeConfig(trans_cell=0.02, rot_bucket_deg=2.0,
                      warp_max_trans=0.06, warp_max_rot_deg=4.0,
                      byte_budget=64 << 20))
  service.add_synthetic_scenes(3, height=H, width=W, planes=P)
  yield service
  service.close()


def test_exact_cell_hit_is_bit_identical_to_its_first_render(svc):
  img1, info1 = svc.render_edge("scene_000", _pose(0.001))
  assert info1["edge"] == "miss" and info1["etag"]
  # The populated frame IS a real render: bit-identical to the
  # scheduler path for the same pose.
  direct = svc.render("scene_000", _pose(0.001))
  assert direct.tobytes() == img1.tobytes()
  img2, info2 = svc.render_edge("scene_000", _pose(0.001))
  assert info2["edge"] == "hit" and info2["etag"] == info1["etag"]
  assert img2.tobytes() == img1.tobytes()
  # A different pose in the same cell shares the cell's bytes.
  img3, info3 = svc.render_edge("scene_000", _pose(0.004))
  assert info3["edge"] == "hit" and img3.tobytes() == img1.tobytes()


def test_near_miss_is_warp_served_under_the_threshold(svc):
  base = _pose(0.0, 0.0, 0.3)
  img0, info0 = svc.render_edge("scene_001", base)
  assert info0["edge"] == "miss"
  # Adjacent cell, pose error 0.025 < warp_max_trans 0.06 -> warp.
  near = _pose(0.025, 0.0, 0.3)
  img1, info1 = svc.render_edge("scene_001", near)
  assert info1["edge"] == "warp" and info1["etag"] is None
  trans, rot = pose_error(near, base)
  assert trans <= svc.edge.config.warp_max_trans
  assert rot <= svc.edge.config.warp_max_rot_deg
  # The warp is a real resample toward the requested pose: finite,
  # frame-shaped, and not the source frame's bytes.
  assert img1.shape == img0.shape and np.isfinite(img1).all()
  assert img1.tobytes() != img0.tobytes()
  # Beyond the radius: a real render populates the new cell.
  far = _pose(0.0, 0.0, -0.4)
  _, info2 = svc.render_edge("scene_001", far)
  assert info2["edge"] == "miss"


def test_swap_scenes_invalidates_and_repopulates_bit_exact(svc):
  pose = _pose(0.002, 0.0, 0.1)
  old, info_old = svc.render_edge("scene_002", pose)
  assert info_old["edge"] == "miss"
  before = svc.events.count("edge_cache_invalidated")
  svc.swap_scenes(
      {"scene_002": synthetic_scene("scene_002", H, W, P, seed=123)})
  assert svc.events.count("edge_cache_invalidated") == before + 1
  new, info_new = svc.render_edge("scene_002", pose)
  # No frame from the old checkpoint survives: fresh render, fresh tag.
  assert info_new["edge"] == "miss" and info_new["etag"] != info_old["etag"]
  assert new.tobytes() != old.tobytes()
  assert svc.render("scene_002", pose).tobytes() == new.tobytes()
  assert svc.stats()["edge"]["invalidations"] >= 1


def test_render_edge_unknown_scene_raises_keyerror(svc):
  from mpi_vision_tpu.obs.trace import Tracer

  tracer = Tracer()
  tr = tracer.start_trace("render", scene_id="nope")
  with pytest.raises(KeyError, match="nope"):
    svc.render_edge("nope", _pose(), trace=tr)
  # The error path owns the trace: it must land finished (with the
  # error) in the tracer, upholding the X-Trace-Id contract.
  assert tracer.finished == 1
  assert "nope" in tracer.snapshot()["recent"][-1]["error"]


def test_render_edge_hit_finishes_its_trace(svc):
  from mpi_vision_tpu.obs.trace import Tracer

  tracer = Tracer()
  pose = _pose(0.3, 0.0, 0.0)
  svc.render_edge("scene_000", pose,
                  trace=tracer.start_trace("render", scene_id="scene_000"))
  svc.render_edge("scene_000", pose,
                  trace=tracer.start_trace("render", scene_id="scene_000"))
  assert tracer.finished == 2  # miss (flight-finished) AND hit
  names = {s["name"] for t in tracer.snapshot()["recent"] for s in t["spans"]}
  assert "edge_hit" in names


def test_stats_and_metrics_expose_edge_families(svc):
  stats = svc.stats()
  assert {"hits", "warp_serves", "misses", "revalidations", "bytes",
          "frames", "invalidations", "hit_rate"} <= set(stats["edge"])
  text = svc.metrics_text()
  for family in ("mpi_serve_edge_hits_total", "mpi_serve_edge_misses_total",
                 "mpi_serve_edge_warp_serves_total", "mpi_serve_edge_bytes",
                 "mpi_serve_edge_revalidations_total"):
    assert family in text


# --- HTTP revalidation ---------------------------------------------------


@pytest.fixture(scope="module")
def http_base(svc):
  httpd = make_http_server(svc)
  threading.Thread(target=httpd.serve_forever, daemon=True).start()
  yield f"http://127.0.0.1:{httpd.server_address[1]}"
  httpd.shutdown()


def _post(base, payload, headers=None):
  req = urllib.request.Request(base + "/render",
                               data=json.dumps(payload).encode(),
                               headers=headers or {})
  try:
    with urllib.request.urlopen(req) as resp:
      return resp.status, dict(resp.headers), resp.read()
  except urllib.error.HTTPError as e:
    with e:
      return e.code, dict(e.headers), e.read()


def test_http_304_revalidation_roundtrip(svc, http_base):
  body = {"scene_id": "scene_000",
          "pose": _pose(0.0, 0.3, 0.0).tolist()}
  status, headers, payload = _post(http_base, body)
  assert status == 200 and headers["X-Edge-Cache"] == "miss"
  etag = headers["ETag"]
  assert etag.startswith('"') and headers["Cache-Control"] == "max-age=5"
  assert json.loads(payload)["scene_id"] == "scene_000"
  # Unconditional repeat: a 200 exact hit under the same strong tag.
  status, headers, _ = _post(http_base, body)
  assert status == 200 and headers["X-Edge-Cache"] == "hit"
  assert headers["ETag"] == etag
  # Conditional repeat: 304, empty body, no render.
  revalidations = svc.stats()["edge"]["revalidations"]
  status, headers, payload = _post(http_base, body,
                                   {"If-None-Match": etag})
  assert status == 304 and payload == b""
  assert headers["ETag"] == etag
  assert headers["X-Edge-Cache"] == "revalidated"
  assert svc.stats()["edge"]["revalidations"] == revalidations + 1
  # After a live reload the old tag stops validating: full 200, new tag.
  svc.swap_scenes(
      {"scene_000": synthetic_scene("scene_000", H, W, P, seed=77)})
  status, headers, payload = _post(http_base, body,
                                   {"If-None-Match": etag})
  assert status == 200 and headers["X-Edge-Cache"] == "miss"
  assert headers["ETag"] != etag and payload


# --- negative caching under queue pressure (ISSUE 15 satellite) ----------


class _FakeClock:
  def __init__(self, t=1000.0):
    self.t = t

  def __call__(self):
    return self.t


def _neg_cache(ttl=5.0, clock=None):
  return EdgeFrameCache(
      EdgeConfig(trans_cell=0.01, rot_bucket_deg=2.0, warp_max_trans=0.05,
                 warp_max_rot_deg=4.0, byte_budget=1 << 20,
                 negative_ttl_s=ttl),
      clock=clock if clock is not None else _FakeClock())


def test_negative_cache_off_by_default_and_validated():
  cache = _cache()  # default config: negative_ttl_s=0 -> disabled
  assert cache.negative_put("s", "d", _pose()) is None
  assert cache.negative_lookup("s", "d", _pose()) is None
  assert cache.stats()["negative_ttl_s"] == 0
  with pytest.raises(ValueError, match="negative_ttl_s"):
    EdgeConfig(trans_cell=0.01, rot_bucket_deg=2.0, warp_max_trans=0.05,
               warp_max_rot_deg=4.0, negative_ttl_s=-1.0)


def test_negative_cache_shed_scoped_to_cell_and_expiring():
  clock = _FakeClock()
  cache = _neg_cache(ttl=5.0, clock=clock)
  assert cache.negative_put("s", "d", _pose(0.001)) == 5.0
  # Any pose inside the same cell sheds, with the REMAINING ttl.
  clock.t += 2.0
  remaining = cache.negative_lookup("s", "d", _pose(0.009))
  assert remaining == pytest.approx(3.0)
  # A different cell / digest / scene is NOT negative-cached: the
  # pressure verdict is per view cell, never scene-wide.
  assert cache.negative_lookup("s", "d", _pose(0.2)) is None
  assert cache.negative_lookup("s", "other", _pose(0.001)) is None
  assert cache.negative_lookup("t", "d", _pose(0.001)) is None
  stats = cache.stats()
  assert stats["negative_hits"] == 1 and stats["negative_entries"] == 1
  # Past the TTL the entry lapses: the next lookup retries the queue.
  clock.t += 3.1
  assert cache.negative_lookup("s", "d", _pose(0.001)) is None
  assert cache.stats()["negative_entries"] == 0
  assert cache.stats()["negative_hits"] == 1  # expiry is not a hit


def test_negative_cache_cleared_by_invalidation():
  clock = _FakeClock()
  cache = _neg_cache(ttl=30.0, clock=clock)
  cache.negative_put("s", "d", _pose(0.001))
  cache.negative_put("other", "d", _pose(0.001))
  cache.invalidate_scene("s")
  # A reload changes the world the verdict was issued against.
  assert cache.negative_lookup("s", "d", _pose(0.001)) is None
  assert cache.negative_lookup("other", "d", _pose(0.001)) is not None
  cache.negative_put("s", "d", _pose(0.001))
  cache.invalidate_tiles("s", [(0, 0)])
  assert cache.negative_lookup("s", "d", _pose(0.001)) is None


def test_render_edge_negative_caches_queue_full_and_sheds_fast():
  """The server-level arc: a queue-full render poisons its view cell
  for the negative TTL, and repeat requests for that cell shed at the
  cache — carrying Retry-After — without re-entering the scheduler."""
  from mpi_vision_tpu.serve.scheduler import QueueFullError

  service = RenderService(
      max_batch=4, max_wait_ms=5.0, use_mesh=False,
      edge=EdgeConfig(trans_cell=0.02, rot_bucket_deg=2.0,
                      warp_max_trans=0.06, warp_max_rot_deg=4.0,
                      byte_budget=1 << 20, negative_ttl_s=30.0))
  try:
    service.add_synthetic_scenes(1, height=H, width=W, planes=P)
    calls = []

    def full_render(scene_id, pose, timeout=60.0, trace=None):
      calls.append(scene_id)
      raise QueueFullError("request queue full (64 waiting)")

    service.scheduler.render = full_render
    pose = _pose(0.4)
    with pytest.raises(QueueFullError) as e1:
      service.render_edge("scene_000", pose)
    assert e1.value.retry_after_s == 30.0  # populated by the shed
    with pytest.raises(QueueFullError, match="negative-cached") as e2:
      service.render_edge("scene_000", pose)
    assert 0 < e2.value.retry_after_s <= 30.0
    assert calls == ["scene_000"]  # the repeat never reached the queue
    edge = service.stats()["edge"]
    assert edge["negative_hits"] == 1 and edge["negative_entries"] == 1
    text = service.metrics_text()
    assert "mpi_serve_edge_negative_hits_total 1" in text
    assert "mpi_serve_edge_negative_entries 1" in text
  finally:
    service.close()
