"""Resilience-layer tests: fault injection, retry, breaker, watchdog.

Everything runs on CPU against ``FaultyEngine`` with deterministic fault
schedules — the outage classes the serving path must survive
(``BENCH_r05.json``'s tunnel drop, hangs, slow dispatches) replayed in
tier-1. The acceptance invariants:

  * a batch that retries through transient faults resolves
    bit-identically to the no-fault render;
  * persistent failure opens the breaker (fast 503 + Retry-After,
    ``/healthz`` -> degraded with reason) and a half-open probe success
    closes it again (``/healthz`` -> ok);
  * an injected hang trips the watchdog inside its deadline and the
    dispatcher survives to serve the next request;
  * no synchronous ``render()`` ever blocks past its timeout, whatever
    fault is in flight.
"""

import json
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import TimeoutError as FuturesTimeoutError
from types import SimpleNamespace

import numpy as np
import pytest

from mpi_vision_tpu.serve import (
    CircuitBreaker,
    CircuitOpenError,
    DispatchTimeoutError,
    Fault,
    FaultyEngine,
    RenderEngine,
    RenderService,
    ResilienceConfig,
    ResilientExecutor,
    RetryPolicy,
    TransientDeviceError,
    classify_error,
    make_http_server,
)
from mpi_vision_tpu.serve.metrics import ServeMetrics
from mpi_vision_tpu.serve.resilience import call_with_watchdog
from mpi_vision_tpu.serve.scheduler import MicroBatcher
from mpi_vision_tpu.serve.server import _Handler

H = W = 16
P = 4


def _pose(tx=0.0, tz=0.0):
  pose = np.eye(4, dtype=np.float32)
  pose[0, 3], pose[2, 3] = tx, tz
  return pose


def make_service(config: ResilienceConfig, cpu_fallback="off",
                 scenes=1, warm=True):
  """A tiny warmed-up service over a FaultyEngine (no faults queued)."""
  eng = FaultyEngine(RenderEngine(use_mesh=False))
  svc = RenderService(engine=eng, resilience=config,
                      cpu_fallback=cpu_fallback, max_batch=4,
                      max_wait_ms=1.0, use_mesh=False)
  svc.add_synthetic_scenes(scenes, height=H, width=W, planes=P)
  if warm:
    svc.warmup()  # compiles outside the watchdog/deadline clocks
  return svc, eng


# --- unit: classification ------------------------------------------------


def test_classify_error():
  assert classify_error(TransientDeviceError("boom")) == "transient"
  assert classify_error(DispatchTimeoutError("slow")) == "transient"
  assert classify_error(CircuitOpenError(5.0)) == "transient"
  assert classify_error(ConnectionResetError("peer")) == "transient"
  assert classify_error(RuntimeError("UNAVAILABLE: tunnel down")) == "transient"
  assert classify_error(RuntimeError("DEADLINE_EXCEEDED: rpc")) == "transient"
  assert classify_error(RuntimeError("Socket closed")) == "transient"
  assert classify_error(RuntimeError("Connection reset by peer")) == "transient"
  assert classify_error(ValueError("bad pose")) == "permanent"
  assert classify_error(KeyError("no scene")) == "permanent"
  assert classify_error(RuntimeError("shape mismatch")) == "permanent"
  # Bad-input types stay permanent even with a transient-looking message.
  assert classify_error(ValueError("UNAVAILABLE-shaped input")) == "permanent"


# --- unit: retry policy --------------------------------------------------


def test_retry_policy_backoff_deterministic_and_bounded():
  import random

  policy = RetryPolicy(max_retries=3, backoff_base_s=0.1, backoff_mult=2.0,
                       backoff_max_s=0.5, jitter=0.1)
  seq_a = [policy.backoff_s(i, random.Random(7)) for i in range(1, 5)]
  seq_b = [policy.backoff_s(i, random.Random(7)) for i in range(1, 5)]
  assert seq_a == seq_b  # seeded jitter replays exactly
  for attempt, backoff in enumerate(seq_a, start=1):
    nominal = min(0.1 * 2.0 ** (attempt - 1), 0.5)
    assert nominal * 0.9 <= backoff <= nominal * 1.1
  assert seq_a[-1] <= 0.55  # cap holds through the jitter band


# --- unit: circuit breaker (fake clock) ----------------------------------


def test_circuit_breaker_state_machine():
  now = [0.0]
  transitions = []
  br = CircuitBreaker(failure_threshold=3, reset_after_s=10.0,
                      clock=lambda: now[0],
                      on_transition=lambda a, b: transitions.append((a, b)))
  assert br.state == CircuitBreaker.CLOSED and br.allow_primary()
  br.record_failure()
  br.record_failure()
  assert br.state == CircuitBreaker.CLOSED  # under threshold
  br.record_success()
  br.record_failure()
  br.record_failure()
  assert br.state == CircuitBreaker.CLOSED  # success reset the streak
  br.record_failure()
  assert br.state == CircuitBreaker.OPEN and br.opens == 1
  assert not br.allow_primary() and not br.would_allow()
  assert br.retry_after_s() == pytest.approx(10.0)

  now[0] = 10.5  # cooldown elapsed: first caller claims the probe
  assert br.allow_primary()
  assert br.state == CircuitBreaker.HALF_OPEN
  assert not br.allow_primary()  # one probe at a time
  br.record_failure()  # probe failed -> re-open, cooldown re-arms
  assert br.state == CircuitBreaker.OPEN and br.opens == 2
  assert br.retry_after_s() == pytest.approx(10.0)

  now[0] = 21.0
  assert br.allow_primary()
  br.record_success()  # probe passed -> closed
  assert br.state == CircuitBreaker.CLOSED and br.allow_primary()
  assert transitions == [
      (CircuitBreaker.CLOSED, CircuitBreaker.OPEN),
      (CircuitBreaker.OPEN, CircuitBreaker.HALF_OPEN),
      (CircuitBreaker.HALF_OPEN, CircuitBreaker.OPEN),
      (CircuitBreaker.OPEN, CircuitBreaker.HALF_OPEN),
      (CircuitBreaker.HALF_OPEN, CircuitBreaker.CLOSED),
  ]


# --- unit: watchdog ------------------------------------------------------


def test_call_with_watchdog_passthrough_and_trip():
  assert call_with_watchdog(lambda: 42, None) == 42
  assert call_with_watchdog(lambda: 42, 5.0) == 42
  with pytest.raises(ValueError, match="inner"):
    call_with_watchdog(lambda: (_ for _ in ()).throw(ValueError("inner")), 5.0)
  gate = threading.Event()
  t0 = time.monotonic()
  with pytest.raises(DispatchTimeoutError, match="abandoned"):
    call_with_watchdog(lambda: gate.wait(30), 0.2)
  assert time.monotonic() - t0 < 5.0
  gate.set()  # free the abandoned thread
  with pytest.raises(DispatchTimeoutError, match="exhausted"):
    call_with_watchdog(lambda: 42, 0.0)


def test_probe_slot_released_on_indeterminate_outcome():
  """A half-open probe that dies to a permanent (bad-input) error or a
  caller-deadline trip must RELEASE the probe slot — otherwise the
  breaker wedges in HALF_OPEN forever and every render 503s even after
  the device recovers."""
  now = [0.0]
  ex = ResilientExecutor(
      ResilienceConfig(max_retries=0, breaker_threshold=1,
                       breaker_reset_s=1.0, watchdog_s=30.0),
      clock=lambda: now[0], sleep=lambda s: None)
  with pytest.raises(TransientDeviceError):
    ex.run(lambda: (_ for _ in ()).throw(TransientDeviceError("down")))
  assert ex.breaker.state == CircuitBreaker.OPEN
  now[0] = 1.5  # cooldown elapsed: next dispatch is the probe
  with pytest.raises(ValueError):  # probe hits a bad-input error
    ex.run(lambda: (_ for _ in ()).throw(ValueError("bad pose")))
  assert ex.breaker.state == CircuitBreaker.HALF_OPEN
  # Slot must be free again: the NEXT dispatch gets to probe, and its
  # success closes the circuit.
  assert ex.run(lambda: 7) == 7
  assert ex.breaker.state == CircuitBreaker.CLOSED


def test_watchdog_none_disables_guard_even_with_deadline():
  """watchdog_s=None (CLI --watchdog-s 0) means NO watchdog thread and no
  dispatch-side timeout — even for requests that carry a deadline."""
  ex = ResilientExecutor(ResilienceConfig(max_retries=0, watchdog_s=None))
  # A call that outlives the deadline still completes inline (the sync
  # caller's future timeout is then the only clock).
  out = ex.run(lambda: (time.sleep(0.05), "done")[1],
               deadline=time.monotonic() + 0.01)
  assert out == "done"
  assert ex.breaker.state == CircuitBreaker.CLOSED


def test_deadline_capped_trip_does_not_open_breaker():
  """A trip bounded by the CALLER's deadline (tighter than watchdog_s)
  says nothing about device health: overload must not read as outage."""
  ex = ResilientExecutor(ResilienceConfig(
      max_retries=0, breaker_threshold=1, watchdog_s=30.0))
  gate = threading.Event()
  try:
    with pytest.raises(DispatchTimeoutError) as excinfo:
      ex.run(lambda: gate.wait(10), deadline=time.monotonic() + 0.2)
    assert ex.breaker.state == CircuitBreaker.CLOSED
    assert excinfo.value.deadline_capped is True  # labeled overload (504)
  finally:
    gate.set()
  # ...but a genuine watchdog_s-bounded hang trip DOES count.
  ex2 = ResilientExecutor(ResilienceConfig(
      max_retries=0, breaker_threshold=1, watchdog_s=0.2))
  gate2 = threading.Event()
  try:
    with pytest.raises(DispatchTimeoutError):
      ex2.run(lambda: gate2.wait(10), deadline=None)
    assert ex2.breaker.state == CircuitBreaker.OPEN
  finally:
    gate2.set()


# --- unit: fault injection -----------------------------------------------


def test_faulty_engine_queue_and_schedule():
  inner = SimpleNamespace(
      render_batch=lambda scene, poses: np.zeros((len(poses), 2, 2, 3)),
      batch_bucket=lambda v: v, describe=lambda: {"devices": 1},
      devices=[], dispatches=0, method="fused", convention=None,
      use_mesh=False)
  eng = FaultyEngine(inner, schedule=lambda idx: Fault("error")
                     if idx == 2 else None)
  eng.fail_next(1)  # queue outranks the schedule
  with pytest.raises(TransientDeviceError):
    eng.render_batch(None, np.zeros((1, 4, 4)))          # idx 0: queued
  assert eng.render_batch(None, np.zeros((1, 4, 4))).shape[0] == 1  # idx 1
  with pytest.raises(TransientDeviceError):
    eng.render_batch(None, np.zeros((1, 4, 4)))          # idx 2: scheduled
  eng.inject(Fault("error", transient=False))
  with pytest.raises(ValueError, match="permanent"):
    eng.render_batch(None, np.zeros((1, 4, 4)))
  assert eng.describe()["fault_injection"]["error"] == 3
  with pytest.raises(ValueError, match="kind"):
    Fault("explode")


# --- acceptance: retry is invisible in the pixels ------------------------


def test_transient_faults_retry_bit_identical():
  svc, eng = make_service(ResilienceConfig(
      max_retries=2, backoff_base_s=0.01, breaker_threshold=5,
      breaker_reset_s=30.0, watchdog_s=60.0))
  try:
    pose = _pose(0.01)
    baseline = svc.render("scene_000", pose)  # no faults
    eng.fail_next(2)  # 2 consecutive transient failures, then clean
    out = svc.render("scene_000", pose)
    np.testing.assert_array_equal(out, baseline)
    assert svc.metrics.retries == 2
    assert svc.resilient.breaker.state == CircuitBreaker.CLOSED
    assert svc.healthz()["status"] == "ok"
  finally:
    svc.close()


def test_permanent_fault_fails_fast_no_retry():
  svc, eng = make_service(ResilienceConfig(
      max_retries=3, backoff_base_s=0.01, breaker_threshold=2,
      watchdog_s=60.0))
  try:
    eng.inject(Fault("error", transient=False, message="bad input injected"))
    with pytest.raises(ValueError, match="bad input"):
      svc.render("scene_000", _pose())
    assert svc.metrics.retries == 0  # permanent: not worth a single retry
    assert svc.metrics.errors_permanent == 1
    # ...and a bad request must not have counted against the device:
    assert svc.resilient.breaker.state == CircuitBreaker.CLOSED
    np.testing.assert_array_equal(  # service still healthy
        svc.render("scene_000", _pose()).shape, (H, W, 3))
  finally:
    svc.close()


# --- acceptance: breaker opens, 503 + Retry-After, probe re-closes -------


def test_breaker_opens_fastfails_and_probe_recloses():
  svc, eng = make_service(ResilienceConfig(
      max_retries=1, backoff_base_s=0.01, breaker_threshold=2,
      breaker_reset_s=0.4, watchdog_s=60.0))
  httpd = make_http_server(svc, port=0)
  threading.Thread(target=httpd.serve_forever, daemon=True).start()
  base = f"http://127.0.0.1:{httpd.server_address[1]}"
  try:
    eng.schedule = lambda idx: Fault("error")  # persistent device failure
    with pytest.raises((TransientDeviceError, CircuitOpenError)):
      svc.render("scene_000", _pose())
    assert svc.resilient.breaker.state == CircuitBreaker.OPEN
    assert svc.metrics.breaker_opens == 1

    # Fast-fail 503 with Retry-After while open (no queue wait).
    body = json.dumps({"scene_id": "scene_000",
                       "pose": _pose().tolist()}).encode()
    t0 = time.monotonic()
    with pytest.raises(urllib.error.HTTPError) as err:
      urllib.request.urlopen(
          urllib.request.Request(base + "/render", data=body), timeout=30)
    assert err.value.code == 503
    assert int(err.value.headers["Retry-After"]) >= 1
    assert time.monotonic() - t0 < 5.0  # fast, not a queue timeout
    assert svc.metrics.breaker_fastfails >= 1

    with urllib.request.urlopen(base + "/healthz", timeout=30) as resp:
      health = json.load(resp)
    assert health["status"] == "degraded"
    assert "circuit open" in health["reason"]
    assert health["breaker"]["state"] == "open"

    # Device recovers; after the cooldown one half-open probe re-closes.
    eng.schedule = None
    time.sleep(0.5)
    out = svc.render("scene_000", _pose())
    assert out.shape == (H, W, 3)
    assert svc.resilient.breaker.state == CircuitBreaker.CLOSED
    with urllib.request.urlopen(base + "/healthz", timeout=30) as resp:
      assert json.load(resp)["status"] == "ok"
  finally:
    httpd.shutdown()
    svc.close()


# --- acceptance: watchdog + dispatcher survival --------------------------


def test_hang_trips_watchdog_and_dispatcher_survives():
  svc, eng = make_service(ResilienceConfig(
      max_retries=2, backoff_base_s=0.01, breaker_threshold=5,
      watchdog_s=2.0))
  try:
    pose = _pose(0.02)
    baseline = svc.render("scene_000", pose)
    eng.inject(Fault("hang", seconds=120.0))  # one dispatch wedges
    t0 = time.monotonic()
    out = svc.render("scene_000", pose, timeout=30.0)
    elapsed = time.monotonic() - t0
    np.testing.assert_array_equal(out, baseline)  # retry after the trip
    assert svc.metrics.watchdog_trips == 1
    assert elapsed < 20.0  # trip at ~watchdog_s, not the 120 s hang
    assert svc.scheduler.dispatcher_alive()
    # The dispatcher is re-armed: next request serves normally.
    np.testing.assert_array_equal(svc.render("scene_000", pose), baseline)
  finally:
    eng.release.set()  # free the abandoned hang thread
    svc.close()


def test_sync_render_never_blocks_past_timeout():
  svc, eng = make_service(ResilienceConfig(
      max_retries=3, backoff_base_s=0.01, breaker_threshold=100,
      watchdog_s=60.0))
  try:
    eng.schedule = lambda idx: Fault("hang", seconds=120.0)  # every dispatch
    t0 = time.monotonic()
    with pytest.raises((FuturesTimeoutError, TransientDeviceError)):
      svc.render("scene_000", _pose(), timeout=1.0)
    assert time.monotonic() - t0 < 10.0
    assert svc.scheduler.dispatcher_alive()
  finally:
    eng.release.set()
    eng.schedule = None
    svc.close()


# --- acceptance: degraded-mode CPU fallback ------------------------------


def test_breaker_open_routes_to_cpu_fallback():
  svc, eng = make_service(ResilienceConfig(
      max_retries=2, backoff_base_s=0.01, breaker_threshold=1,
      breaker_reset_s=60.0, watchdog_s=60.0), cpu_fallback="on")
  try:
    assert svc.fallback_engine is not None
    pose = _pose(0.015)
    baseline = svc.render("scene_000", pose)
    eng.schedule = lambda idx: Fault("error")  # primary hard down
    # threshold=1: the first failure opens the breaker; the retry inside
    # the SAME request degrades to the CPU fallback transparently.
    out = svc.render("scene_000", pose)
    np.testing.assert_array_equal(out, baseline)
    assert svc.metrics.fallback_renders >= 1
    health = svc.healthz()
    assert health["status"] == "degraded"
    assert "CPU fallback" in health["reason"]
    assert health["fallback_active"] is True
    # Submissions do NOT fast-fail while a fallback can serve them.
    np.testing.assert_array_equal(svc.render("scene_000", pose), baseline)
  finally:
    svc.close()


def test_prebake_fallback_serves_breaker_open_requests_warm():
  """--prebake-fallback: the first degraded render must be a fallback-
  cache HIT (the CPU bake was paid at startup), not a cold bake inside
  an already-degraded request."""
  svc, eng = make_service(ResilienceConfig(
      max_retries=2, backoff_base_s=0.01, breaker_threshold=1,
      breaker_reset_s=60.0, watchdog_s=60.0), cpu_fallback="on", scenes=3)
  try:
    warmed = svc.prebake_fallback(2)  # hottest-K = first two registered
    assert warmed == ["scene_000", "scene_001"]
    fb = svc._fallback_cache.stats()
    assert fb["scenes"] == 2 and fb["misses"] == 2
    eng.schedule = lambda idx: Fault("error")  # primary hard down
    out = svc.render("scene_000", _pose(0.01))  # degrades to fallback
    assert out.shape == (H, W, 3)
    assert svc.metrics.fallback_renders >= 1
    fb = svc._fallback_cache.stats()
    assert fb["hits"] >= 1 and fb["misses"] == 2  # WARM: no new bake
    # An un-prebaked scene still works — it just pays the cold bake.
    svc.render("scene_002", _pose(0.01))
    assert svc._fallback_cache.stats()["misses"] == 3
  finally:
    svc.close()


def test_prebake_fallback_without_fallback_engine_is_a_noop():
  svc, _ = make_service(ResilienceConfig(watchdog_s=60.0),
                        cpu_fallback="off", warm=False)
  try:
    assert svc.prebake_fallback(2) == []
  finally:
    svc.close()


# --- healthz state machine ----------------------------------------------


def test_healthz_unhealthy_after_close():
  svc, _ = make_service(ResilienceConfig(watchdog_s=60.0), warm=False)
  httpd = make_http_server(svc, port=0)
  threading.Thread(target=httpd.serve_forever, daemon=True).start()
  base = f"http://127.0.0.1:{httpd.server_address[1]}"
  try:
    svc.close()
    health = svc.healthz()
    assert health["status"] == "unhealthy"
    assert "closed" in health["reason"]
    # Status-code probes must see non-2xx once unhealthy.
    with pytest.raises(urllib.error.HTTPError) as err:
      urllib.request.urlopen(base + "/healthz", timeout=30)
    assert err.value.code == 503
    assert json.load(err.value)["status"] == "unhealthy"
  finally:
    httpd.shutdown()


def test_cpu_fallback_on_requires_resilience():
  with pytest.raises(ValueError, match="requires resilience"):
    RenderService(resilience=None, cpu_fallback="on", use_mesh=False)


# --- satellites ----------------------------------------------------------


def test_metrics_snapshot_has_error_accounting():
  m = ServeMetrics()
  m.record_error("transient", count=2)
  m.record_error("permanent")
  m.record_error("deadline")
  m.record_rejected()
  m.record_retry()
  m.record_watchdog_trip()
  m.record_fallback()
  m.record_breaker_open()
  m.record_breaker_fastfail()
  m.record_client_disconnect()
  snap = m.snapshot()
  assert snap["errors"] == {"transient": 2, "permanent": 1, "deadline": 1}
  assert snap["rejected"] == 1
  assert snap["resilience"] == {
      "retries": 1, "watchdog_trips": 1, "fallback_renders": 1,
      "breaker_opens": 1, "breaker_fastfails": 1, "client_disconnects": 1}
  assert json.loads(json.dumps(snap)) == snap
  m.reset()
  assert m.snapshot()["errors"] == {
      "transient": 0, "permanent": 0, "deadline": 0}


class _BrokenPipeWriter:
  def write(self, data):
    raise BrokenPipeError("client went away")


def test_client_disconnect_counted_not_raised():
  metrics = ServeMetrics()
  handler = SimpleNamespace(
      service=SimpleNamespace(metrics=metrics),
      send_response=lambda *a: None, send_header=lambda *a: None,
      end_headers=lambda: None, wfile=_BrokenPipeWriter(),
      close_connection=False)
  _Handler._send_bytes(handler, b'{"status": "ok"}')  # must not raise
  assert metrics.client_disconnects == 1
  assert handler.close_connection is True


def test_binary_render_roundtrip():
  svc, _ = make_service(ResilienceConfig(watchdog_s=60.0))
  httpd = make_http_server(svc, port=0)
  threading.Thread(target=httpd.serve_forever, daemon=True).start()
  base = f"http://127.0.0.1:{httpd.server_address[1]}"
  try:
    pose = _pose(0.01)
    req = urllib.request.Request(
        base + "/render",
        data=json.dumps({"scene_id": "scene_000",
                         "pose": pose.tolist()}).encode(),
        headers={"Accept": "application/octet-stream"})
    with urllib.request.urlopen(req, timeout=120) as resp:
      raw = resp.read()
      shape = tuple(int(d) for d in
                    resp.headers["X-Image-Shape"].split(","))
      dtype = resp.headers["X-Image-Dtype"]
      assert resp.headers["Content-Type"] == "application/octet-stream"
      assert resp.headers["X-Scene-Id"] == "scene_000"
    img = np.frombuffer(raw, dtype).reshape(shape)
    reference = svc.render("scene_000", pose)
    np.testing.assert_array_equal(img, reference)
    # Binary is the size win the ROADMAP asked for: raw f32 vs base64.
    assert len(raw) == reference.nbytes
  finally:
    httpd.shutdown()
    svc.close()


def test_cold_scene_bake_failure_degrades_to_fallback():
  """A cache-miss bake onto a dead device must fail over exactly like a
  failed render: retried, counted by the breaker, served by the CPU
  fallback — not forwarded raw to every caller."""
  def dead_provider(sid):
    raise TransientDeviceError("UNAVAILABLE: bake on dead device")

  class _Unreachable:
    def render_batch(self, scene, poses):
      raise AssertionError("primary render must not be reached")

  class _FallbackEngine:
    def render_batch(self, scene, poses):
      return np.zeros((len(poses), 2, 2, 3), np.float32)

  ex = ResilientExecutor(ResilienceConfig(
      max_retries=1, backoff_base_s=0.001, breaker_threshold=1,
      breaker_reset_s=60.0, watchdog_s=30.0))
  mb = MicroBatcher(_Unreachable(), dead_provider, resilient=ex,
                    fallback_engine=_FallbackEngine(),
                    fallback_scene_provider=lambda sid: None,
                    max_batch=2, max_wait_ms=0.0).start()
  try:
    out = mb.render("s", _pose(), timeout=30.0)
    assert out.shape == (2, 2, 3)  # degraded, but served
    assert ex.breaker.state == CircuitBreaker.OPEN  # bake failure counted
  finally:
    mb.stop()


def test_scheduler_submit_cancel_timeout_stress():
  """Hammer submit/cancel/timeout races against a slow engine: the
  dispatcher must never die to InvalidStateError and queue depth must
  return to 0 once the storm passes."""
  class _SlowEngine:
    def render_batch(self, scene, poses):
      time.sleep(0.003)
      return np.zeros((len(poses), 2, 2, 3), np.float32)

  mb = MicroBatcher(_SlowEngine(), scene_provider=lambda sid: None,
                    max_batch=4, max_wait_ms=0.5, max_queue=256).start()
  stop = threading.Event()
  outcomes = {"ok": 0, "cancelled": 0, "timeout": 0}
  lock = threading.Lock()

  def hammer(idx):
    from mpi_vision_tpu.serve.scheduler import QueueFullError

    rng = np.random.default_rng(idx)
    while not stop.is_set():
      roll = rng.random()
      try:
        if roll < 0.4:  # submit then cancel immediately (race the claim)
          fut = mb.submit(f"scene_{idx % 3}", _pose())
          fut.cancel()
          with lock:
            outcomes["cancelled"] += 1
        elif roll < 0.7:  # sync render with a timeout that often loses
          mb.render(f"scene_{idx % 3}", _pose(), timeout=0.002)
          with lock:
            outcomes["ok"] += 1
        else:  # plain render, generous timeout
          mb.render(f"scene_{idx % 3}", _pose(), timeout=30.0)
          with lock:
            outcomes["ok"] += 1
      except (FuturesTimeoutError, DispatchTimeoutError):
        with lock:
          outcomes["timeout"] += 1
      except QueueFullError:
        time.sleep(0.001)  # shed: back off and keep hammering
      except RuntimeError:
        return  # scheduler stopping: not what this test is about

  threads = [threading.Thread(target=hammer, args=(i,), daemon=True)
             for i in range(8)]
  for t in threads:
    t.start()
  time.sleep(1.5)
  stop.set()
  for t in threads:
    t.join(30)
  try:
    assert mb.dispatcher_alive()  # survived every cancellation race
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
      if mb.metrics.snapshot()["queue_depth"] == 0:
        break
      time.sleep(0.02)
    assert mb.metrics.snapshot()["queue_depth"] == 0
    assert outcomes["ok"] > 0 and outcomes["cancelled"] > 0
  finally:
    mb.stop()


def test_serve_cli_sigterm_graceful_shutdown():
  """``python -m mpi_vision_tpu serve`` under SIGTERM must drain and exit
  0 with its JSON summary — containers send SIGTERM, not KeyboardInterrupt,
  and a hard kill would drop in-flight requests on the floor."""
  import os
  import signal
  import subprocess
  import sys

  repo = os.path.dirname(os.path.dirname(os.path.dirname(
      os.path.abspath(__file__))))
  sys.path.insert(0, repo)
  from _cpu_mesh import hardened_env

  env = hardened_env(1)
  env["JAX_COMPILATION_CACHE_DIR"] = os.path.join(repo, ".jax_cache")
  proc = subprocess.Popen(
      [sys.executable, "-m", "mpi_vision_tpu", "serve", "--scenes", "1",
       "--img-size", "16", "--num-planes", "4", "--port", "0",
       "--duration", "300", "--no-warmup"],
      stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
      env=env, cwd=repo)
  stderr_lines = []
  try:
    deadline = time.monotonic() + 300
    listening = False
    while time.monotonic() < deadline:
      line = proc.stderr.readline()
      if not line:
        break
      stderr_lines.append(line)
      if "listening on" in line:
        listening = True
        break
    assert listening, f"server never came up:\n{''.join(stderr_lines)}"
    proc.send_signal(signal.SIGTERM)
    try:
      stdout, stderr = proc.communicate(timeout=240)
    except subprocess.TimeoutExpired:
      proc.kill()
      stdout, stderr = proc.communicate()
      raise AssertionError(
          "server did not exit within 240s of SIGTERM\n"
          f"stdout:\n{stdout}\nstderr:\n{''.join(stderr_lines)}{stderr}")
    stderr_lines.append(stderr)
  finally:
    if proc.poll() is None:
      proc.kill()
      proc.communicate()
  assert proc.returncode == 0, f"rc={proc.returncode}:\n{''.join(stderr_lines)}"
  summary = json.loads(stdout.strip().splitlines()[-1])
  assert summary["command"] == "serve"
  # The drain message comes from the normal teardown path; the handler's
  # own log line is best-effort (a signal landing mid-stderr-write may
  # legitimately skip it).
  assert "drained and closed" in "".join(stderr_lines)
