"""Self-healing fleet supervisor: unit state machine + real-process pins.

Two layers, cheapest first:

  * ``FleetSupervisor`` unit tests over fakes — a fake pool, a fake
    transport, and a fake clock drive every edge of the state machine
    deterministically: exit/wedge detection, exponential restart
    backoff, crash-loop quarantine at the budget, wedge recovery
    without a restart, rolling-restart sequencing, eject/readmit
    integration with a real ``Router``.
  * The multi-process acceptance tests (ISSUE 9's tier-1 chaos drill):
    3 REAL serve backends — SIGKILL one and the supervisor restarts it
    on its old port, the router's breaker re-closes through the
    half-open probe, and renders come back bit-identical; a crash-loop
    variant pins quarantine after exactly the restart budget (with
    ``mpi_cluster_quarantines_total`` visible at the router and the
    remaining replicas serving every scene); a rolling restart over the
    live 3-backend pool replaces every process with zero failed client
    requests.
"""

import base64
import json
import os
import signal
import sys
import threading
import time

import numpy as np
import pytest

from mpi_vision_tpu.obs import parse_metrics_text
from mpi_vision_tpu.serve.cluster import (
    BackendPool,
    FleetSupervisor,
    Router,
)
from mpi_vision_tpu.serve.resilience import RestartBudget, RetryBudget

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


# --- budget units --------------------------------------------------------


class FakeClock:
  def __init__(self, t=1000.0):
    self.t = t

  def __call__(self):
    return self.t


def test_restart_budget_window_slides():
  clock = FakeClock()
  budget = RestartBudget(max_restarts=2, window_s=10.0, clock=clock)
  assert budget.try_spend() and budget.try_spend()
  assert not budget.try_spend()  # exhausted
  assert budget.remaining() == 0 and budget.snapshot()["refused"] == 1
  clock.t += 10.1  # the window slides past both spends
  assert budget.remaining() == 2
  assert budget.try_spend()


def test_retry_budget_deposits_and_refuses_when_dry():
  budget = RetryBudget(ratio=0.5, initial=1.0, cap=2.0)
  assert budget.try_withdraw()
  assert not budget.try_withdraw()  # dry
  for _ in range(4):  # 4 * 0.5 = 2 tokens, capped at 2
    budget.deposit()
  assert budget.try_withdraw() and budget.try_withdraw()
  assert not budget.try_withdraw()
  snap = budget.snapshot()
  assert snap["withdrawals"] == 3 and snap["refused"] == 2


# --- supervisor over fakes ----------------------------------------------


class FakePool:
  """Process-control fake: alive flags flip on kill/restart; every call
  is recorded so tests assert the exact kill/respawn sequence."""

  def __init__(self, backends=("b0", "b1", "b2")):
    self.addrs = {b: f"host-{b}:1" for b in backends}
    self._alive = {b: True for b in backends}
    self.kills: list[tuple[str, int]] = []
    self.restarts: list[str] = []
    self.fail_restarts: set[str] = set()

  def addresses(self):
    return dict(self.addrs)

  def alive(self, backend_id):
    return self._alive[backend_id]

  def kill(self, backend_id, sig=signal.SIGKILL):
    self.kills.append((backend_id, sig))
    self._alive[backend_id] = False

  def restart(self, backend_id):
    self.restarts.append(backend_id)
    if backend_id in self.fail_restarts:
      raise RuntimeError("spawn failed")
    self._alive[backend_id] = True
    return self.addrs[backend_id]

  def die(self, backend_id):  # the crash itself (no signal recorded)
    self._alive[backend_id] = False


class FakeTransport:
  """address -> handler(method, path) -> (status, headers, body);
  raising ConnectionError simulates a dead/hung host."""

  def __init__(self):
    self.handlers = {}

  def set(self, address, handler):
    self.handlers[address] = handler

  def set_health(self, address, status):
    def handler(method, path):
      if path == "/healthz":
        return 200, {}, json.dumps({"status": status}).encode()
      if path == "/stats":
        return 200, {}, json.dumps({"queue_depth": 0}).encode()
      return 404, {}, b"{}"
    self.handlers[address] = handler

  def set_dead(self, address):
    def handler(method, path):
      raise ConnectionError("connection refused")
    self.handlers[address] = handler

  def request(self, method, url, body=None, headers=None, timeout=30.0):
    address, _, path = url[len("http://"):].partition("/")
    return self.handlers[address]("GET", "/" + path)


def _fake_fleet(clock=None, router=True, **sup_kwargs):
  clock = clock if clock is not None else FakeClock()
  pool = FakePool()
  transport = FakeTransport()
  for addr in pool.addrs.values():
    transport.set_health(addr, "ok")
  rt = None
  events = None
  if router:
    rt = Router(pool.addrs, replication=2, transport=transport,
                clock=clock)
    events = rt.events  # one log tells the whole story (the CLI wiring)
  sup = FleetSupervisor(
      pool, router=rt, events=events, transport=transport, clock=clock,
      sleep=lambda s: None, load_refresh_s=0, **sup_kwargs)
  return pool, transport, rt, sup, clock


def test_supervisor_restarts_a_dead_backend_and_readmits():
  pool, transport, router, sup, clock = _fake_fleet()
  pool.die("b1")
  sup.tick()
  assert pool.restarts == ["b1"] and pool.alive("b1")
  assert sup.state("b1") == FleetSupervisor.UP
  assert router.ejected() == []  # ejected on detection, readmitted after
  assert router.metrics.snapshot()["restarts"] == {"b1": 1}
  events = sup.events.snapshot()["by_kind"]
  assert events["backend_restart"] == 1
  assert events.get("backend_eject", 0) == 1  # router-side edges logged
  assert events.get("backend_readmit", 0) == 1


def test_supervisor_wedged_backend_is_killed_and_replaced():
  pool, transport, router, sup, clock = _fake_fleet(wedge_after=3)
  transport.set_dead(pool.addrs["b2"])  # alive but not answering
  for _ in range(2):
    sup.tick()
  assert pool.restarts == []  # below wedge_after: not declared dead yet
  sup.tick()  # 3rd consecutive failure: wedged -> SIGKILL -> respawn
  assert ("b2", signal.SIGKILL) in pool.kills
  assert pool.restarts == ["b2"]
  assert sup.snapshot()["backends"]["b2"]["restarts"] == 1
  # A persistently-unhealthy answer wedges the same way a timeout does
  # (this one is a repeat inside the budget window, so it backs off).
  transport.set_health(pool.addrs["b2"], "unhealthy")
  for _ in range(3):
    sup.tick()
  assert pool.restarts == ["b2"]  # detected; 0.5s backoff cooling
  clock.t += 0.6
  sup.tick()
  assert pool.restarts == ["b2", "b2"]


def test_supervisor_degraded_backend_is_left_alone():
  pool, transport, router, sup, clock = _fake_fleet(wedge_after=1)
  transport.set_health(pool.addrs["b0"], "degraded")
  for _ in range(5):
    sup.tick()
  # Degraded answers (CPU fallback, SLO burn): restarting it would turn
  # a partial failure into a total one.
  assert pool.restarts == [] and pool.kills == []
  assert sup.state("b0") == FleetSupervisor.UP


def test_supervisor_exponential_backoff_between_crash_loop_restarts():
  pool, transport, router, sup, clock = _fake_fleet(
      restart_budget=10, budget_window_s=1000.0, backoff_base_s=0.5,
      backoff_mult=2.0, backoff_max_s=8.0)
  pool.die("b1")
  sup.tick()
  assert len(pool.restarts) == 1  # first restart of an episode: immediate
  pool.die("b1")  # crashed right back
  clock.t += 0.1
  sup.tick()  # detection starts the 0.5s backoff clock
  assert len(pool.restarts) == 1  # still cooling
  clock.t += 0.4
  sup.tick()
  assert len(pool.restarts) == 1  # 0.4 < 0.5: still cooling
  clock.t += 0.1
  sup.tick()
  assert len(pool.restarts) == 2
  pool.die("b1")
  sup.tick()  # detection: second repeat backs off 1.0s
  clock.t += 0.6
  sup.tick()
  assert len(pool.restarts) == 2  # 0.6 < 1.0: still cooling
  clock.t += 0.5
  sup.tick()
  assert len(pool.restarts) == 3


def test_supervisor_backoff_resets_after_a_long_healthy_run():
  pool, transport, router, sup, clock = _fake_fleet(
      restart_budget=10, budget_window_s=60.0, backoff_base_s=0.5)
  pool.die("b1")
  sup.tick()
  pool.die("b1")
  sup.tick()  # detection: 0.5s backoff (a repeat crash)
  clock.t += 0.6
  sup.tick()
  assert len(pool.restarts) == 2
  clock.t += 61.0  # ran past the budget window: not a crash loop
  pool.die("b1")
  sup.tick()
  assert len(pool.restarts) == 3  # immediate again, no carried backoff


def test_supervisor_quarantines_a_crash_looper_at_the_budget():
  pool, transport, router, sup, clock = _fake_fleet(
      restart_budget=2, budget_window_s=1000.0, backoff_base_s=0.1,
      backoff_max_s=0.1)
  for _ in range(5):
    pool.die("b1")
    sup.tick()
    clock.t += 0.2  # clear every backoff
    sup.tick()
  assert sup.state("b1") == FleetSupervisor.QUARANTINED
  assert len(pool.restarts) == 2  # exactly the budget, then containment
  assert sup.quarantined() == ["b1"]
  # Quarantine is sticky: more ticks, no more respawns.
  for _ in range(5):
    clock.t += 1.0
    sup.tick()
  assert len(pool.restarts) == 2
  # The router ejected it for good and counts the quarantine — and the
  # eject reason ESCALATED from the transient crash reason to the
  # permanent verdict (an operator reading /stats must see why it is
  # out of rotation NOW, not why it first went down).
  assert router.ejected() == ["b1"]
  assert router.stats()["backend_info"]["b1"]["eject_reason"] \
      == "quarantined"
  assert router.metrics.snapshot()["quarantines"] == {"b1": 1}
  families = parse_metrics_text(router._cluster_registry().render())
  assert families["mpi_cluster_quarantines_total"]["samples"][
      ("mpi_cluster_quarantines_total", (("backend", "b1"),))] == 1
  assert families["mpi_cluster_backend_up"]["samples"][
      ("mpi_cluster_backend_up", (("backend", "b1"),))] == 0
  assert sup.events.count("backend_quarantined") == 1


def test_supervisor_failed_respawn_counts_and_retries_until_quarantine():
  pool, transport, router, sup, clock = _fake_fleet(
      restart_budget=3, budget_window_s=1000.0, backoff_base_s=0.1,
      backoff_max_s=0.1)
  pool.fail_restarts.add("b0")
  pool.die("b0")
  for _ in range(10):
    sup.tick()
    clock.t += 0.2
  assert sup.state("b0") == FleetSupervisor.QUARANTINED
  snap = sup.snapshot()["backends"]["b0"]
  assert snap["restart_failures"] == 3 and snap["restarts"] == 0


def test_supervisor_wedge_that_recovers_is_readmitted_without_restart():
  pool, transport, router, sup, clock = _fake_fleet(
      wedge_after=1, backoff_base_s=5.0)
  pool.fail_restarts.add("b2")  # the respawn fails: backend stays down
  transport.set_dead(pool.addrs["b2"])
  sup.tick()
  assert sup.state("b2") == FleetSupervisor.DOWN
  assert router.ejected() == ["b2"]
  pool.fail_restarts.clear()
  pool._alive["b2"] = True  # the zombie un-wedged on its own
  transport.set_health(pool.addrs["b2"], "ok")
  sup.tick()
  assert sup.state("b2") == FleetSupervisor.UP
  assert router.ejected() == []  # back in rotation, no restart burned
  assert sup.snapshot()["backends"]["b2"]["restarts"] == 0


def test_supervisor_readmit_clears_quarantine_and_respawns():
  pool, transport, router, sup, clock = _fake_fleet(
      restart_budget=1, budget_window_s=1000.0, backoff_base_s=0.1)
  pool.die("b1")
  sup.tick()
  pool.die("b1")
  clock.t += 0.2
  sup.tick()
  clock.t += 0.2
  sup.tick()
  assert sup.state("b1") == FleetSupervisor.QUARANTINED
  sup.readmit("b1")
  assert sup.state("b1") == FleetSupervisor.UP and pool.alive("b1")
  assert router.ejected() == []
  assert sup.snapshot()["backends"]["b1"]["budget"]["remaining"] == 1


def test_supervisor_rolling_restart_sequences_and_reports():
  pool, transport, router, sup, clock = _fake_fleet()
  report = sup.rolling_restart(drain_s=0.0)
  assert report["ok"] and report["backends"] == ["b0", "b1", "b2"]
  assert pool.restarts == ["b0", "b1", "b2"]  # one at a time, in order
  # Planned downtime drains via SIGTERM, never SIGKILL.
  assert [k for k in pool.kills] == [
      ("b0", signal.SIGTERM), ("b1", signal.SIGTERM),
      ("b2", signal.SIGTERM)]
  assert router.ejected() == []  # every step readmitted its backend
  by_kind = sup.events.snapshot()["by_kind"]
  assert by_kind["rolling_restart_begin"] == 1
  assert by_kind["rolling_restart_step"] == 3
  assert by_kind["rolling_restart_end"] == 1
  assert all(s["breaker"] == "closed" for s in report["steps"])
  # No restart budget burned: planned restarts are not crashes.
  assert all(b["budget"]["in_window"] == 0
             for b in sup.snapshot()["backends"].values())


def test_supervisor_rolling_restart_skips_quarantined_and_reports_failure():
  pool, transport, router, sup, clock = _fake_fleet(
      restart_budget=1, budget_window_s=1000.0, backoff_base_s=0.1)
  pool.die("b0")
  sup.tick()
  pool.die("b0")
  clock.t += 0.2
  sup.tick()
  clock.t += 0.2
  sup.tick()
  assert sup.state("b0") == FleetSupervisor.QUARANTINED
  pool.fail_restarts.add("b2")
  report = sup.rolling_restart(drain_s=0.0)
  assert report["backends"] == ["b1", "b2"]  # quarantined b0 skipped
  assert not report["ok"]
  failed = next(s for s in report["steps"] if s["backend"] == "b2")
  assert "error" in failed and not failed["ok"]
  # The failed step leaves b2 to the monitor loop: down + ejected.
  assert sup.state("b2") == FleetSupervisor.DOWN
  assert "b2" in router.ejected()


def test_supervisor_feeds_router_load_table():
  clock = FakeClock()
  pool = FakePool()
  transport = FakeTransport()
  for b, addr in pool.addrs.items():
    def handler(method, path, _b=b):
      if path == "/healthz":
        return 200, {}, json.dumps({"status": "ok"}).encode()
      if path == "/stats":
        depth = {"b0": 9, "b1": 0, "b2": 1}[_b]
        return 200, {}, json.dumps({"queue_depth": depth}).encode()
      return 404, {}, b"{}"
    transport.set(addr, handler)
  router = Router(pool.addrs, replication=2, transport=transport,
                  clock=clock, load_threshold=4)
  sup = FleetSupervisor(pool, router=router, transport=transport,
                        clock=clock, sleep=lambda s: None,
                        load_refresh_s=1.0)
  sup.tick()
  with router._lock:
    assert {b: d for b, (d, _) in router._load.items()} == {
        "b0": 9.0, "b1": 0.0, "b2": 1.0}


# --- the real thing: supervised multi-process fleet on CPU ---------------


@pytest.fixture(scope="module")
def fleet(healed_backends):
  """3 real serve processes + a router with short-cooldown per-backend
  breakers (0.5 s: a restarted backend's half-open probe re-closes
  within the test's traffic, not after minutes). The pool is the
  session-shared one (conftest.backend_pool), re-gated healthy here;
  the tests below run in definition order against it and leave it
  fully serving (3 live backends) for the next suite."""
  pool, backends = healed_backends
  router = Router(backends, replication=2, breaker_threshold=2,
                  breaker_reset_s=0.5, render_timeout_s=120.0)
  yield pool, router


def _render_body(sid, tx=0.0):
  pose = np.eye(4)
  pose[0, 3] = tx
  return json.dumps({"scene_id": sid, "pose": pose.tolist()}).encode()


def _decode(body):
  payload = json.loads(body)
  img = np.frombuffer(base64.b64decode(payload["image_b64"]), "<f4")
  return img.reshape(payload["shape"])


def _supervisor(pool, router, **kwargs):
  kwargs.setdefault("probe_s", 0.05)
  kwargs.setdefault("backoff_base_s", 0.05)
  kwargs.setdefault("backoff_max_s", 0.2)
  kwargs.setdefault("load_refresh_s", 0)
  return FleetSupervisor(
      pool, router=router, events=router.events,
      log=lambda m: print(m, file=sys.stderr), **kwargs)


def test_fleet_sigkill_restart_breaker_recloses_bit_identical(fleet):
  """THE acceptance arc: SIGKILL -> supervisor respawns on the same
  port -> the router's breaker re-closes through its half-open probe ->
  the restarted backend serves bit-identical pixels."""
  pool, router = fleet
  sids = pool.scene_ids()
  victim = router.placement(sids[0])[0]
  vsid = sids[0]
  status, headers, body = router.forward_render(vsid, _render_body(vsid))
  assert status == 200 and headers["X-Backend-Id"] == victim
  baseline = _decode(body)

  pool.kill(victim)
  # Traffic meets the corpse: two failed attempts open ITS breaker
  # (threshold 2) while replicas keep answering.
  for _ in range(2):
    status, headers, _ = router.forward_render(vsid, _render_body(vsid))
    assert status == 200 and headers["X-Backend-Id"] != victim
  assert router.breaker_state(victim) == "open"

  sup = _supervisor(pool, router, restart_budget=5, budget_window_s=30.0)
  sup.tick()  # one monitor pass: detect exit, respawn, readmit
  assert pool.alive(victim)
  assert sup.state(victim) == FleetSupervisor.UP
  assert router.events.count("backend_restart") >= 1
  assert router.metrics.snapshot()["restarts"].get(victim, 0) >= 1

  # The breaker is still open; once the 0.5 s cooldown elapses the next
  # request IS the half-open probe and its success re-closes the
  # circuit — after which the victim serves its primary scene again.
  deadline = time.monotonic() + 60.0
  served = None
  while time.monotonic() < deadline:
    status, headers, body = router.forward_render(vsid, _render_body(vsid))
    assert status == 200
    if headers["X-Backend-Id"] == victim:
      served = _decode(body)
      break
    time.sleep(0.05)
  assert served is not None, "restarted backend never served again"
  assert router.breaker_state(victim) == "closed"
  np.testing.assert_array_equal(served, baseline)  # bit-identical


def test_fleet_rolling_restart_zero_failed_requests(fleet):
  """Rolling restart over 3 LIVE backends: every process is replaced,
  one at a time, while closed-loop clients hammer the router — and not
  one client request fails."""
  pool, router = fleet
  sids = pool.scene_ids()
  pids_before = {b: pool.pid(b) for b in pool.addresses()}
  sup = _supervisor(pool, router)

  stop = threading.Event()
  failures: list[str] = []
  ok_counts = [0] * 3
  lock = threading.Lock()

  def worker(w):
    i = 0
    while not stop.is_set():
      sid = sids[(w + i) % len(sids)]
      i += 1
      try:
        status, _, _ = router.forward_render(
            sid, _render_body(sid, tx=0.002 * (i % 5)))
      except Exception as e:  # noqa: BLE001 - any escape is a failure
        with lock:
          failures.append(f"{sid}: {e!r}")
        continue
      if status == 200:
        ok_counts[w] += 1
      else:
        with lock:
          failures.append(f"{sid}: http {status}")

  threads = [threading.Thread(target=worker, args=(w,), daemon=True)
             for w in range(3)]
  for t in threads:
    t.start()
  deadline = time.monotonic() + 60.0
  while sum(ok_counts) < 5 and time.monotonic() < deadline:
    time.sleep(0.05)  # traffic established before the roll
  report = sup.rolling_restart(drain_s=0.5, settle_timeout_s=60.0)
  # Keep loading briefly after the roll: the fleet must be fully back.
  end = time.monotonic() + 1.0
  while time.monotonic() < end:
    time.sleep(0.05)
  stop.set()
  for t in threads:
    t.join(30)

  assert report["ok"], report
  assert [s["backend"] for s in report["steps"]] == sorted(pids_before)
  assert failures == [], failures[:10]  # ZERO failed client requests
  assert sum(ok_counts) > 0
  pids_after = {b: pool.pid(b) for b in pool.addresses()}
  assert all(pids_after[b] != pids_before[b] for b in pids_before), (
      "rolling restart must replace every process")
  assert router.ejected() == []
  for b in pool.addresses():
    assert router.breaker_state(b) == "closed"
  assert router.events.count("rolling_restart_begin") >= 1
  assert router.events.count("rolling_restart_step") >= 3
  assert router.events.count("rolling_restart_end") >= 1


def test_fleet_crash_loop_quarantined_within_budget(fleet):
  """THE containment pin: a backend that dies every time it comes back
  is quarantined after exactly its restart budget — respawns stop, the
  event and router metric fire, and the remaining replicas keep serving
  every scene."""
  pool, router = fleet
  sids = pool.scene_ids()
  victim = router.placement(sids[0])[0]
  budget = 2
  sup = _supervisor(pool, router, restart_budget=budget,
                    budget_window_s=300.0).start()
  try:
    kills = 0
    deadline = time.monotonic() + 120.0
    while time.monotonic() < deadline and \
        sup.state(victim) != FleetSupervisor.QUARANTINED:
      if sup.state(victim) in (None, FleetSupervisor.UP) \
          and pool.alive(victim):
        pool.kill(victim)
        kills += 1
      time.sleep(0.02)
    assert sup.state(victim) == FleetSupervisor.QUARANTINED, (
        f"not quarantined after {kills} kills: {sup.snapshot()}")
    snap = sup.snapshot()["backends"][victim]
    assert snap["restarts"] == budget  # contained AT the budget
    assert router.events.count("backend_quarantined") == 1
    # Containment means containment: no further respawns.
    time.sleep(0.5)
    assert not pool.alive(victim)
    assert sup.snapshot()["backends"][victim]["restarts"] == budget
    # Visible at the router: ejected + quarantine counter + /metrics.
    assert victim in router.ejected()
    assert router.metrics.snapshot()["quarantines"] == {victim: 1}
    families = parse_metrics_text(router.metrics_text())
    assert families["mpi_cluster_quarantines_total"]["samples"][
        ("mpi_cluster_quarantines_total", (("backend", victim),))] == 1
    # The fleet keeps serving EVERY scene off the surviving replicas.
    for sid in sids:
      status, headers, _ = router.forward_render(sid, _render_body(sid))
      assert status == 200 and headers["X-Backend-Id"] != victim
    health = router.healthz()
    assert health["status"] == "degraded"  # honest, but not dead
    # Operator readmit: fresh budget, respawn, back in rotation.
    sup.readmit(victim)
    assert pool.alive(victim) and victim not in router.ejected()
    deadline = time.monotonic() + 60.0
    while time.monotonic() < deadline:
      status, headers, _ = router.forward_render(
          sids[0], _render_body(sids[0]))
      assert status == 200
      if headers["X-Backend-Id"] == victim:
        break
      time.sleep(0.05)
    assert router.breaker_state(victim) in ("closed", "half_open")
  finally:
    sup.stop()
