"""SLO engine + event log tests.

The acceptance pin for PR 8 lives here: an end-to-end,
``serve_load --chaos --dry``-style run in which an injected fault window
makes the availability burn-rate alert FIRE — visible simultaneously in
``/healthz`` (degraded with the SLO reason), ``/stats`` (the ``slo``
block), ``/metrics`` (``mpi_slo_alert_firing`` = 1), and the
``serve_load`` JSON verdict block — and then CLEAR after recovery, with
all four surfaces agreeing again. Plus the burn-rate unit math (window
rotation, fast/slow fire+clear edges) on fake clocks, the
``/debug/events`` + ``/debug/traces?id=`` endpoints, the router's
cross-process aggregation of all three, and the ``/debug/profile``
artifact-upload hook.
"""

import contextlib
import json
import time
import urllib.parse
import urllib.request

import numpy as np
import pytest

from mpi_vision_tpu.obs import DeviceProfiler, parse_metrics_text
from mpi_vision_tpu.obs.events import EventLog, file_sink
from mpi_vision_tpu.obs.slo import SloConfig, SloTracker, verdict
from mpi_vision_tpu.obs.trace import Tracer
from mpi_vision_tpu.serve import (
    FaultyEngine,
    RenderEngine,
    RenderService,
    make_http_server,
)
from mpi_vision_tpu.serve.cluster.router import Router

H = W = 16
P = 4


class FakeClock:
  def __init__(self, t=1000.0):
    self.t = t

  def __call__(self):
    return self.t

  def advance(self, dt):
    self.t += dt
    return self.t


def _pose(tx=0.0):
  pose = np.eye(4, dtype=np.float32)
  pose[0, 3] = tx
  return pose


def _cfg(**kw):
  base = dict(fast_window_s=10.0, slow_window_s=60.0, bucket_s=1.0,
              burn_threshold=10.0, min_requests=5)
  base.update(kw)
  return SloConfig(**base)


# --- burn-rate math -------------------------------------------------------


class TestSloTracker:

  def test_idle_tracker_is_quiet(self):
    t = SloTracker(_cfg(), clock=FakeClock())
    snap = t.snapshot()
    assert snap["alerts_firing"] == []
    for obj in snap["objectives"].values():
      assert obj["fast"]["requests"] == 0
      assert obj["fast"]["burn_rate"] == 0.0
      assert obj["fast"]["attained"] is None

  def test_window_rotation_ages_out_bad_events(self):
    clock = FakeClock()
    t = SloTracker(_cfg(), clock=clock)
    for _ in range(8):
      t.record(ok=False)
    snap = t.snapshot()["objectives"]["availability"]
    assert snap["fast"]["bad"] == 8 and snap["slow"]["bad"] == 8
    clock.advance(11)  # past the fast window, inside the slow one
    snap = t.snapshot()["objectives"]["availability"]
    assert snap["fast"]["bad"] == 0
    assert snap["slow"]["bad"] == 8
    clock.advance(60)  # past the slow window too
    snap = t.snapshot()["objectives"]["availability"]
    assert snap["slow"]["requests"] == 0 and snap["slow"]["bad"] == 0

  def test_availability_alert_fires_and_clears_on_fast_window(self):
    clock = FakeClock()
    alerts = []
    t = SloTracker(_cfg(), clock=clock,
                   on_alert=lambda n, f, d: alerts.append((n, f, d)))
    # Healthy traffic: no alert.
    for _ in range(20):
      t.record(ok=True, latency_s=0.01)
    assert t.alerts_firing() == []
    # Fault window: burn far above threshold in BOTH windows.
    for _ in range(10):
      t.record(ok=False)
    assert t.alerts_firing() == ["availability"]
    fire = [a for a in alerts if a[1]]
    assert fire and fire[0][0] == "availability"
    assert fire[0][2]["fast_burn"] >= 10.0
    snap = t.snapshot()["objectives"]["availability"]["alert"]
    assert snap["firing"] is True and snap["fired"] == 1
    assert snap["for_s"] >= 0
    # Recovery: the bad events age out of the fast window (the slow
    # window still carries them) -> the alert clears on the fast edge.
    clock.advance(11)
    for _ in range(5):
      t.record(ok=True, latency_s=0.01)
    assert t.alerts_firing() == []
    slow_burn = t.snapshot()["objectives"]["availability"]["slow"]
    assert slow_burn["bad"] == 10  # history retained; alert cleared anyway
    clear = [a for a in alerts if not a[1]]
    assert clear and clear[0][0] == "availability"
    snap = t.snapshot()["objectives"]["availability"]["alert"]
    assert snap["firing"] is False and snap["cleared"] == 1

  def test_latency_objective_scores_only_completed_requests(self):
    clock = FakeClock()
    t = SloTracker(_cfg(latency_threshold_s=0.1), clock=clock)
    for _ in range(6):
      t.record(ok=True, latency_s=0.5)   # completed but slow
    for _ in range(4):
      t.record(ok=False)                 # errors: availability only
    snap = t.snapshot()["objectives"]
    assert snap["latency"]["fast"]["requests"] == 6
    assert snap["latency"]["fast"]["bad"] == 6
    assert snap["availability"]["fast"]["requests"] == 10
    assert snap["availability"]["fast"]["bad"] == 4
    assert "latency" in t.alerts_firing()

  def test_min_requests_guards_idle_spikes(self):
    t = SloTracker(_cfg(min_requests=50), clock=FakeClock())
    for _ in range(10):
      t.record(ok=False)
    assert t.alerts_firing() == []  # 10 < min_requests: no page

  def test_slow_window_must_confirm_the_fast_one(self):
    # A fresh burst after a long good history: fast window is hot but
    # the slow window's burn stays under threshold -> no alert.
    clock = FakeClock()
    t = SloTracker(_cfg(), clock=clock)
    for _ in range(5000):
      t.record(ok=True, latency_s=0.01)
    clock.advance(20)  # history leaves the fast window, stays in the slow
    for _ in range(6):
      t.record(ok=False)
    snap = t.snapshot()["objectives"]["availability"]
    assert snap["fast"]["burn_rate"] >= 10.0
    assert snap["slow"]["burn_rate"] < 10.0
    assert t.alerts_firing() == []

  def test_registry_agrees_with_snapshot(self):
    clock = FakeClock()
    t = SloTracker(_cfg(), clock=clock)
    for i in range(30):
      t.record(ok=i % 3 != 0, latency_s=0.01)
    snap = t.snapshot()
    families = parse_metrics_text(t.registry(snap).render())

    def val(name, labels):
      return families[name]["samples"][(name, tuple(sorted(labels)))]

    for slo in ("availability", "latency"):
      obj = snap["objectives"][slo]
      assert val("mpi_slo_objective_target",
                 [("slo", slo)]) == obj["target"]
      for window in ("fast", "slow"):
        labels = [("slo", slo), ("window", window)]
        assert val("mpi_slo_window_requests", labels) \
            == obj[window]["requests"]
        assert val("mpi_slo_window_bad", labels) == obj[window]["bad"]
        assert val("mpi_slo_burn_rate", labels) \
            == pytest.approx(obj[window]["burn_rate"])
      assert val("mpi_slo_alert_firing", [("slo", slo)]) \
          == (1 if obj["alert"]["firing"] else 0)
      assert val("mpi_slo_alerts_fired_total", [("slo", slo)]) \
          == obj["alert"]["fired"]
    assert families["mpi_slo_burn_rate"]["type"] == "gauge"
    assert families["mpi_slo_alerts_fired_total"]["type"] == "counter"

  def test_verdict_block_shape(self):
    t = SloTracker(_cfg(), clock=FakeClock())
    for _ in range(20):
      t.record(ok=True, latency_s=0.01)
    v = verdict(t.snapshot())
    assert v["pass"] is True and v["alerts_firing"] == []
    for obj in v["objectives"].values():
      assert {"target", "attained", "requests", "burn_fast", "burn_slow",
              "alerts_fired", "pass"} <= set(obj)
    assert verdict(None) is None  # SLO-disabled services


# --- event log ------------------------------------------------------------


class TestEventLog:

  def test_ring_bounds_and_counts(self):
    clock = FakeClock()
    log = EventLog(capacity=4, clock=clock)
    for i in range(7):
      log.emit("tick", i=i)
    snap = log.snapshot()
    assert snap["emitted"] == 7 and snap["dropped"] == 3
    assert [e["i"] for e in snap["events"]] == [3, 4, 5, 6]
    assert snap["by_kind"] == {"tick": 7}
    assert all(e["ts_unix_s"] == pytest.approx(clock.t)
               for e in snap["events"])
    assert log.count("tick") == 7 and log.count("nope") == 0

  def test_kind_filter_and_recent_bound(self):
    log = EventLog(clock=FakeClock())
    log.emit("a", x=1)
    log.emit("b", x=2)
    log.emit("a", x=3)
    snap = log.snapshot(kind="a")
    assert [e["x"] for e in snap["events"]] == [1, 3]
    assert len(log.snapshot(recent=1)["events"]) == 1

  def test_file_sink_appends_jsonl(self, tmp_path):
    path = str(tmp_path / "events.jsonl")
    sink = file_sink(path)
    log = EventLog(clock=FakeClock(), sink=sink)
    log.emit("breaker", old="closed", new="open")
    log.emit("breaker", old="open", new="half_open")
    sink.close()
    lines = [json.loads(l) for l in open(path).read().splitlines()]
    assert [l["new"] for l in lines] == ["open", "half_open"]
    assert all(l["kind"] == "breaker" for l in lines)

  def test_failing_sink_is_counted_never_raised(self):
    def bad_sink(line):
      raise OSError("disk full")

    log = EventLog(clock=FakeClock(), sink=bad_sink)
    log.emit("tick")
    assert log.sink_errors == 1 and log.emitted == 1


# --- end-to-end: fault window -> alert -> recovery (the acceptance pin) ---


def _get(port, path):
  with urllib.request.urlopen(
      f"http://127.0.0.1:{port}{path}", timeout=30) as resp:
    return resp.status, resp.read()


def _get_json(port, path):
  status, body = _get(port, path)
  return status, json.loads(body)


@pytest.fixture
def faulty_slo_service():
  """A serve_load --chaos --dry style rig: real service + scheduler over
  a FaultyEngine, SLO tracker on an injectable clock so window edges are
  deterministic."""
  clock = FakeClock()
  tracker = SloTracker(_cfg(), clock=clock)
  engine = FaultyEngine(RenderEngine(use_mesh=False))
  svc = RenderService(engine=engine, resilience=None, max_batch=2,
                      max_wait_ms=1.0, slo=tracker, tracer=Tracer(),
                      metrics_ttl_s=0.0)
  svc.add_synthetic_scenes(1, height=H, width=W, planes=P)
  svc.warmup()
  yield svc, engine, tracker, clock
  svc.close()


def test_slo_alert_fires_and_clears_across_all_surfaces(faulty_slo_service):
  svc, engine, tracker, clock = faulty_slo_service
  httpd = make_http_server(svc)
  port = httpd.server_address[1]
  import threading

  threading.Thread(target=httpd.serve_forever, daemon=True).start()
  try:
    # Phase 1 — healthy traffic: ok everywhere.
    for i in range(8):
      svc.render("scene_000", _pose(0.001 * i), timeout=60)
    status, health = _get_json(port, "/healthz")
    assert status == 200 and health["status"] == "ok"
    assert health["slo_alerts_firing"] == []

    # Phase 2 — the injected fault window: every dispatch fails, the
    # availability burn crosses threshold in both windows.
    for i in range(10):
      engine.fail_next(1)
      with pytest.raises(Exception, match="UNAVAILABLE"):
        svc.render("scene_000", _pose(0.001 * i), timeout=60)
    assert tracker.alerts_firing() == ["availability"]

    _, health = _get_json(port, "/healthz")
    assert health["status"] == "degraded"
    assert "SLO alert firing" in health["reason"]
    assert "availability" in health["reason"]
    assert health["slo_alerts_firing"] == ["availability"]

    _, stats = _get_json(port, "/stats")
    slo = stats["slo"]
    assert slo["alerts_firing"] == ["availability"]
    avail = slo["objectives"]["availability"]
    assert avail["alert"]["firing"] is True and avail["alert"]["fired"] == 1
    assert avail["fast"]["burn_rate"] >= 10.0

    _, body = _get(port, "/metrics")
    families = parse_metrics_text(body.decode())
    firing = families["mpi_slo_alert_firing"]["samples"]
    assert firing[("mpi_slo_alert_firing",
                   (("slo", "availability"),))] == 1
    # /metrics agrees with /stats on the new families (the PR-3 pin,
    # extended to mpi_slo_*).
    assert families["mpi_slo_window_bad"]["samples"][
        ("mpi_slo_window_bad",
         (("slo", "availability"), ("window", "fast")))] \
        == avail["fast"]["bad"]

    # The serve_load JSON slo verdict block judges the same state.
    v = verdict(slo)
    assert v["alerts_firing"] == ["availability"]
    assert v["objectives"]["availability"]["pass"] is False
    assert v["pass"] is False

    # Phase 3 — recovery: faults stop, the fast window drains, good
    # traffic resumes; the alert clears on every surface.
    clock.advance(11)
    for i in range(8):
      svc.render("scene_000", _pose(0.001 * i), timeout=60)
    assert tracker.alerts_firing() == []
    _, health = _get_json(port, "/healthz")
    assert health["status"] == "ok"
    assert health["slo_alerts_firing"] == []
    _, stats = _get_json(port, "/stats")
    alert = stats["slo"]["objectives"]["availability"]["alert"]
    assert alert["firing"] is False
    assert alert["fired"] == 1 and alert["cleared"] == 1
    _, body = _get(port, "/metrics")
    families = parse_metrics_text(body.decode())
    assert families["mpi_slo_alert_firing"]["samples"][
        ("mpi_slo_alert_firing", (("slo", "availability"),))] == 0

    # The lifecycle record: fire AND clear landed in /debug/events.
    _, events = _get_json(port, "/debug/events?kind=slo_alert")
    edges = [(e["slo"], e["firing"]) for e in events["events"]]
    assert ("availability", True) in edges
    assert ("availability", False) in edges
  finally:
    httpd.shutdown()


def test_healthz_appends_slo_reason_to_breaker_degradation():
  # Breaker-degraded AND SLO-firing must both show up in the reason.
  clock = FakeClock()
  tracker = SloTracker(_cfg(), clock=clock)
  svc = RenderService(use_mesh=False, slo=tracker, metrics_ttl_s=0.0)
  try:
    for _ in range(20):
      tracker.record(ok=False)
    assert tracker.alerts_firing() == ["availability"]
    health = svc.healthz()
    assert health["status"] == "degraded"
    assert "SLO alert firing" in health["reason"]
  finally:
    svc.close()


# --- /debug endpoints -----------------------------------------------------


def test_debug_traces_id_filter_returns_one_trace():
  svc = RenderService(use_mesh=False, tracer=Tracer(), metrics_ttl_s=0.0)
  svc.add_synthetic_scenes(1, height=H, width=W, planes=P)
  httpd = make_http_server(svc)
  port = httpd.server_address[1]
  import threading

  threading.Thread(target=httpd.serve_forever, daemon=True).start()
  try:
    _, tid = svc.render_traced("scene_000", _pose())
    svc.render_traced("scene_000", _pose(0.01))  # a second, different trace
    _, found = _get_json(port, f"/debug/traces?id={tid}")
    assert found["trace_id"] == tid
    assert len(found["traces"]) == 1
    assert found["traces"][0]["trace_id"] == tid
    assert any(s["name"] == "dispatch" for s in found["traces"][0]["spans"])
    _, missing = _get_json(port, "/debug/traces?id=deadbeefdeadbeef")
    assert missing["traces"] == []
  finally:
    httpd.shutdown()
    svc.close()


def test_scene_swap_and_breaker_events_reach_debug_events(tmp_path):
  from mpi_vision_tpu.serve.server import synthetic_scene

  svc = RenderService(use_mesh=False, metrics_ttl_s=0.0)
  httpd = make_http_server(svc)
  port = httpd.server_address[1]
  import threading

  threading.Thread(target=httpd.serve_forever, daemon=True).start()
  try:
    svc.add_synthetic_scenes(1, height=H, width=W, planes=P)
    svc.swap_scenes({"scene_000": synthetic_scene("scene_000", H, W, P,
                                                  seed=7)})
    _, events = _get_json(port, "/debug/events")
    kinds = [e["kind"] for e in events["events"]]
    assert "scene_swap" in kinds
    swap = next(e for e in events["events"] if e["kind"] == "scene_swap")
    assert swap["scenes"] == ["scene_000"]
    assert events["emitted"] >= 1
    # recent must be validated, not crash the handler.
    status, _ = _get(port, "/debug/events?recent=2")
    assert status == 200
  finally:
    httpd.shutdown()
    svc.close()


def test_breaker_transitions_emit_events():
  engine = FaultyEngine(RenderEngine(use_mesh=False))
  from mpi_vision_tpu.serve import ResilienceConfig

  svc = RenderService(
      engine=engine, max_batch=1, max_wait_ms=0.5, metrics_ttl_s=0.0,
      resilience=ResilienceConfig(max_retries=0, breaker_threshold=2,
                                  breaker_reset_s=60.0, watchdog_s=None),
      cpu_fallback="off")
  svc.add_synthetic_scenes(1, height=H, width=W, planes=P)
  try:
    svc.warmup()
    engine.fail_next(2)
    for _ in range(2):
      with pytest.raises(Exception):  # noqa: B017 - any transient error
        svc.render("scene_000", _pose(), timeout=30)
    snap = svc.events.snapshot(kind="breaker")
    assert [(e["old"], e["new"]) for e in snap["events"]] \
        == [("closed", "open")]
  finally:
    engine.release.set()
    svc.close()


# --- profile artifact-upload hook -----------------------------------------


def _fake_profiler(tmp_path):
  return DeviceProfiler(str(tmp_path), trace_ctx=lambda d: contextlib.nullcontext(),
                        clock=FakeClock(), sleep=lambda s: None)


def test_profile_hook_receives_capture_dir(tmp_path):
  seen = []
  svc = RenderService(use_mesh=False, profiler=_fake_profiler(tmp_path),
                      profile_hook=seen.append, metrics_ttl_s=0.0)
  try:
    result = svc.profile(0.5)
    assert result["hook"] == "ok"
    assert seen == [result["logdir"]]
    assert svc.profile_hook_failures == 0
    assert svc.stats()["profile"] == {"captures": 1, "hook_failures": 0}
  finally:
    svc.close()


def test_profile_hook_failure_is_counted_never_fatal(tmp_path):
  def bad_hook(path):
    raise RuntimeError("upload refused")

  svc = RenderService(use_mesh=False, profiler=_fake_profiler(tmp_path),
                      profile_hook=bad_hook, metrics_ttl_s=0.0)
  try:
    result = svc.profile(0.5)  # must NOT raise
    assert result["hook"].startswith("failed:")
    assert svc.profile_hook_failures == 1
    assert svc.stats()["profile"]["hook_failures"] == 1
    assert svc.events.count("profile_hook_failed") == 1
    # The capture machinery is intact for the next call.
    assert svc.profile(0.5)["capture"] == 2
  finally:
    svc.close()


# --- alert delivery hook (the serving twin of --profile-hook) -------------


def _drive_alert_fire(tracker):
  for _ in range(20):
    tracker.record(ok=True, latency_s=0.01)
  for _ in range(10):
    tracker.record(ok=False)


def _await_hook_runs(svc, n, timeout=10.0):
  deadline = time.monotonic() + timeout
  while time.monotonic() < deadline:
    stats = svc.stats()
    if stats.get("alert_hook", {}).get("runs", 0) >= n:
      return stats["alert_hook"]
    time.sleep(0.01)
  raise AssertionError(
      f"alert hook never reached {n} runs: {svc.stats().get('alert_hook')}")


def test_alert_hook_delivers_fire_and_clear_edges():
  clock = FakeClock()
  tracker = SloTracker(_cfg(), clock=clock)
  seen = []
  svc = RenderService(use_mesh=False, slo=tracker, alert_hook=seen.append,
                      metrics_ttl_s=0.0)
  try:
    _drive_alert_fire(tracker)
    assert tracker.alerts_firing() == ["availability"]
    hook_stats = _await_hook_runs(svc, 1)
    assert hook_stats["failures"] == 0
    fire = seen[0]
    # The hook receives the full slo_alert event record — the same one
    # /debug/events carries — so a pager script needs no second lookup.
    assert fire["kind"] == "slo_alert" and fire["slo"] == "availability"
    assert fire["firing"] is True and fire["fast_burn"] >= 10.0
    assert "seq" in fire and "ts_unix_s" in fire
    # Recovery delivers the CLEAR edge too (a pager that only hears
    # fires never stands down).
    clock.advance(11)
    for _ in range(5):
      tracker.record(ok=True, latency_s=0.01)
    assert tracker.alerts_firing() == []
    _await_hook_runs(svc, 2)
    clears = [r for r in seen if r["firing"] is False]
    assert clears and clears[0]["slo"] == "availability"
  finally:
    svc.close()


def test_alert_hook_failure_is_counted_never_fatal():
  clock = FakeClock()
  tracker = SloTracker(_cfg(), clock=clock)

  def bad_hook(record):
    raise RuntimeError("pager webhook down")

  svc = RenderService(use_mesh=False, slo=tracker, alert_hook=bad_hook,
                      metrics_ttl_s=0.0)
  try:
    _drive_alert_fire(tracker)  # must NOT raise into the record path
    assert tracker.alerts_firing() == ["availability"]
    hook_stats = _await_hook_runs(svc, 1)
    assert hook_stats["failures"] == 1
    assert svc.events.count("alert_hook_failed") == 1
    # The alert itself still fired everywhere else.
    assert svc.events.count("slo_alert") == 1
  finally:
    svc.close()


# --- router aggregation (fake transport, no sockets) ----------------------


class FakeBackendTransport:
  """Canned per-backend GET responses keyed by (address, path)."""

  def __init__(self, responses):
    self.responses = responses  # {address: {path: payload-dict}}

  def request(self, method, url, body=None, headers=None, timeout=30.0):
    parsed = urllib.parse.urlsplit(url)
    address = parsed.netloc
    path = parsed.path + ("?" + parsed.query if parsed.query else "")
    backend = self.responses.get(address)
    if backend is None:
      raise ConnectionError("refused")
    payload = backend.get(path)
    if payload is None:
      payload = {"error": f"unknown path {path}"}
    return 200, {"Content-Type": "application/json"}, \
        json.dumps(payload).encode()


def _backend_slo_block(firing, bad, total):
  attained = None if total == 0 else round(1.0 - bad / total, 6)
  def win():
    return {"window_s": 60.0, "requests": total, "bad": bad,
            "attained": attained, "burn_rate": 0.0 if not total
            else round((bad / total) / 0.01, 4)}
  return {
      "config": {"burn_threshold": 10.0},
      "objectives": {
          "availability": {
              "target": 0.99, "fast": win(), "slow": win(),
              "alert": {"firing": firing, "fired": int(firing),
                        "cleared": 0}},
          "latency": {
              "target": 0.95,
              "fast": {"window_s": 60.0, "requests": total, "bad": 0,
                       "attained": 1.0 if total else None,
                       "burn_rate": 0.0},
              "slow": {"window_s": 600.0, "requests": total, "bad": 0,
                       "attained": 1.0 if total else None,
                       "burn_rate": 0.0},
              "alert": {"firing": False, "fired": 0, "cleared": 0}},
      },
      "alerts_firing": ["availability"] if firing else [],
      "alert_errors": 0,
  }


def test_router_aggregates_slo_state_across_backends():
  transport = FakeBackendTransport({
      "h1:1": {"/stats": {"requests": 10,
                          "slo": _backend_slo_block(True, 50, 100)}},
      "h2:2": {"/stats": {"requests": 10,
                          "slo": _backend_slo_block(False, 0, 100)}},
  })
  router = Router({"b1": "h1:1", "b2": "h2:2"}, transport=transport)
  slo = router.stats()["slo"]
  assert slo["backends_reporting"] == 2
  assert slo["alerts_firing"] == {"b1": ["availability"]}
  assert slo["worst"]["availability"]["backend"] == "b1"
  att = slo["attainment"]["availability"]
  assert att["requests"] == 200 and att["bad"] == 50
  assert att["attained"] == pytest.approx(0.75)


def test_router_debug_events_merges_router_and_backends():
  transport = FakeBackendTransport({
      "h1:1": {"/debug/events?recent=128": {
          "emitted": 2, "dropped": 0, "sink_errors": 0, "capacity": 512,
          "by_kind": {"breaker": 2},
          "events": [{"seq": 1, "kind": "breaker"}]}},
  })
  router = Router({"b1": "h1:1"}, transport=transport)
  router.events.emit("failover", scene_id="s", to_backend="b1")
  snap = router.events_snapshot()
  assert snap["router"]["by_kind"] == {"failover": 1}
  assert snap["backends"]["b1"]["emitted"] == 2


def test_router_trace_search_stitches_cross_process_tree():
  tid = "a" * 32
  backend_trace = {"trace_id": tid, "name": "render", "duration_ms": 5.0,
                   "error": None,
                   "spans": [{"id": 1, "parent": 0, "name": "dispatch",
                              "t0_ms": 0.0, "duration_ms": 4.0}]}
  transport = FakeBackendTransport({
      "h1:1": {f"/debug/traces?id={tid}": {"trace_id": tid,
                                           "traces": [backend_trace]}},
      "h2:2": {f"/debug/traces?id={tid}": {"trace_id": tid, "traces": []}},
  })
  clock = FakeClock()
  tracer = Tracer(clock=clock)
  router = Router({"b1": "h1:1", "b2": "h2:2"}, transport=transport,
                  tracer=tracer, clock=clock)
  tr = tracer.start_trace("route", trace_id=tid)
  span = tr.start_span("forward", backend="b1")
  clock.advance(0.004)
  tr.end_span(span)
  tr.finish()
  stitched = router.find_trace(tid)
  assert stitched["trace_id"] == tid
  assert stitched["processes"] == 2         # router + the one backend hit
  assert len(stitched["router"]) == 1
  assert stitched["backends"] == {"b1": [backend_trace]}
  assert stitched["spans_total"] == 2       # router's forward + backend's
  # An id nobody recorded is an empty, well-formed answer.
  missing = router.find_trace("b" * 32)
  assert missing["processes"] == 0 and missing["spans_total"] == 0


def test_router_metrics_drop_non_additive_slo_gauges():
  """Pool-summing a 0.99 target across 3 backends must NOT export 2.97
  (nor let one idle backend's NaN attainment poison the fleet): the
  ratio/target/threshold slo gauges are dropped from the aggregate,
  while the summable slices (window counts, alert one-hots) survive."""
  tracker = SloTracker(_cfg(), clock=FakeClock())
  tracker.record(ok=True, latency_s=0.01)
  text = tracker.metrics_text()

  class MetricsTransport:
    def request(self, method, url, body=None, headers=None, timeout=30.0):
      assert url.endswith("/metrics?exemplars=1")
      return 200, {"Content-Type": "text/plain"}, text.encode()

  router = Router({"b1": "h1:1", "b2": "h2:2"},
                  transport=MetricsTransport(), metrics_ttl_s=0.0)
  families = parse_metrics_text(router.metrics_text())
  for dropped in ("mpi_slo_objective_target", "mpi_slo_attainment_ratio",
                  "mpi_slo_burn_rate", "mpi_slo_burn_threshold",
                  "mpi_slo_latency_threshold_seconds"):
    assert dropped not in families, dropped
  # Summable slices aggregate across the pool.
  assert families["mpi_slo_window_requests"]["samples"][
      ("mpi_slo_window_requests",
       (("slo", "availability"), ("window", "fast")))] == 2
  assert families["mpi_slo_alert_firing"]["samples"][
      ("mpi_slo_alert_firing", (("slo", "availability"),))] == 0
  assert "mpi_cluster_backends" in families


def test_router_failover_emits_event():
  class FailFirstTransport:
    def request(self, method, url, body=None, headers=None, timeout=30.0):
      if "h1:1" in url:
        raise ConnectionError("dead host")
      return 200, {"Content-Type": "application/json"}, json.dumps({
          "scene_id": "s", "shape": [1, 1, 3],
          "image_b64": "A" * 16}).encode()

  router = Router({"b1": "h1:1", "b2": "h2:2"},
                  transport=FailFirstTransport())
  # Force placement order: walk replicas until the dead one is primary.
  sid = next(s for s in ("s%d" % i for i in range(64))
             if router.placement(s)[0] == "b1")
  status, headers, _ = router.forward_render(sid, b"{}")
  assert status == 200 and headers["X-Backend-Id"] == "b2"
  snap = router.events.snapshot(kind="failover")
  assert len(snap["events"]) == 1
  assert snap["events"][0]["to_backend"] == "b2"
