"""In-process serve/ subsystem tests: cache, scheduler, metrics, HTTP.

Everything runs through ``RenderService``'s pure-Python API (plus one
socketed HTTP round-trip) on tiny scenes; the acceptance invariant is
that micro-batching is *invisible* in the pixels — a request's image is
bit-identical whether it rode a coalesced batch or a lone dispatch.
"""

import base64
import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from mpi_vision_tpu.serve import (
    RenderService,
    SceneCache,
    bake_scene,
    make_http_server,
    synthetic_scene,
)
from mpi_vision_tpu.serve.metrics import ServeMetrics, percentile

H = W = 16
P = 4


def _pose(tx=0.0, tz=0.0):
  pose = np.eye(4, dtype=np.float32)
  pose[0, 3], pose[2, 3] = tx, tz
  return pose


@pytest.fixture(scope="module")
def svc():
  service = RenderService(max_batch=4, max_wait_ms=250.0, use_mesh=False)
  service.add_synthetic_scenes(2, height=H, width=W, planes=P)
  yield service
  service.close()


# --- cache ---------------------------------------------------------------


def _baked(sid, seed=0):
  return bake_scene(sid, *synthetic_scene(sid, H, W, P, seed=seed))


def test_cache_lru_eviction_and_counters():
  one = _baked("a").nbytes
  cache = SceneCache(byte_budget=2 * one)  # room for two scenes
  for sid in ("a", "b", "c"):
    assert cache.get(sid) is None  # 3 misses
    cache.put(_baked(sid))
  assert len(cache) == 2 and "a" not in cache  # LRU evicted
  assert cache.get("c").scene_id == "c"
  assert cache.get("b") is not None  # b now most recent
  cache.put(_baked("d"))  # evicts c (LRU after the b touch)
  assert "c" not in cache and "b" in cache
  stats = cache.stats()
  assert stats["evictions"] == 2 and stats["misses"] == 3
  assert stats["hits"] == 2 and stats["hit_rate"] == pytest.approx(0.4)
  assert stats["bytes"] <= stats["byte_budget"]


def test_cache_keeps_newest_scene_over_budget():
  cache = SceneCache(byte_budget=1)  # smaller than any scene
  cache.put(_baked("a"))
  assert "a" in cache  # must still serve


def test_bake_scene_validates_shapes():
  rgba, depths, k = synthetic_scene("s", H, W, P)
  with pytest.raises(ValueError, match="rgba_layers"):
    bake_scene("s", rgba[..., :3], depths, k)
  with pytest.raises(ValueError, match="depths"):
    bake_scene("s", rgba, depths[:-1], k)
  with pytest.raises(ValueError, match="intrinsics"):
    bake_scene("s", rgba, depths, k[:2])


# --- metrics -------------------------------------------------------------


def test_percentile_nearest_rank():
  vals = sorted(range(1, 101))
  assert percentile(vals, 0.50) == 51  # nearest rank on 0..99 indices
  assert percentile(vals, 0.99) == 99
  assert percentile([7.0], 0.99) == 7.0


def test_metrics_snapshot_schema():
  m = ServeMetrics(window=8)
  for lat in (0.010, 0.020, 0.030):
    m.record_request(lat)
  m.record_batch(3, 0.025)
  m.set_queue_depth(5)
  snap = m.snapshot(cache_stats={"hit_rate": 0.5})
  assert snap["requests"] == 3 and snap["batches"] == 1
  assert snap["batch_size_hist"] == {"3": 1}
  assert snap["queue_depth"] == 5 and snap["cache"]["hit_rate"] == 0.5
  assert snap["latency_ms"]["p50"] == pytest.approx(20.0)
  assert snap["latency_ms"]["p99"] == pytest.approx(30.0)
  assert snap["renders_per_sec"] > 0


# --- scheduler + engine: the acceptance invariant ------------------------


def test_concurrent_requests_coalesce_and_match_unbatched(svc):
  """>= 2 concurrent same-scene requests ride ONE device dispatch and
  each result is bit-identical to its unbatched render."""
  poses = [_pose(0.01 * i, -0.005 * i) for i in range(4)]
  before = svc.engine.dispatches
  futs = [svc.render_async("scene_000", p) for p in poses]
  outs = [f.result(120) for f in futs]
  assert svc.engine.dispatches - before == 1  # one coalesced dispatch
  hist = svc.stats()["batch_size_hist"]
  assert max(int(k) for k in hist) >= 2
  for pose, out in zip(poses, outs):
    solo = svc.render("scene_000", pose)  # its own batch-of-1 dispatch
    assert out.dtype == np.float32
    np.testing.assert_array_equal(out, solo)


def test_mixed_scene_requests_batch_per_scene(svc):
  futs = [svc.render_async("scene_000", _pose(0.01)),
          svc.render_async("scene_001", _pose(0.01)),
          svc.render_async("scene_000", _pose(0.02))]
  outs = [f.result(120) for f in futs]
  # Different scenes render differently; same scheduler, no cross-talk.
  assert not np.array_equal(outs[0], outs[1])
  np.testing.assert_array_equal(
      outs[2], svc.render("scene_000", _pose(0.02)))


def test_unknown_scene_fails_that_request_only(svc):
  bad = svc.render_async("no_such_scene", _pose())
  good = svc.render_async("scene_000", _pose())
  with pytest.raises(KeyError, match="no_such_scene"):
    bad.result(120)
  assert good.result(120).shape == (H, W, 3)


def test_stats_serving_schema(svc):
  svc.render("scene_000", _pose(0.03))
  stats = svc.stats()
  assert json.loads(json.dumps(stats)) == stats  # JSON-clean
  for key in ("p50", "p95", "p99"):
    assert stats["latency_ms"][key] > 0
  assert stats["renders_per_sec"] > 0
  assert 0 < stats["cache"]["hit_rate"] <= 1
  assert stats["engine"]["devices"] >= 1
  assert stats["uptime_s"] > 0 and stats["queue_depth"] == 0


def test_scheduler_rejects_bad_pose(svc):
  with pytest.raises(ValueError, match="pose"):
    svc.render_async("scene_000", np.eye(3))


def test_queue_full_sheds_load():
  """Past max_queue, submissions fail fast with QueueFullError (the HTTP
  layer's 503) instead of growing a dead backlog."""
  import time

  from mpi_vision_tpu.serve.scheduler import MicroBatcher, QueueFullError

  gate = threading.Event()

  class _GateEngine:
    dispatches = 0

    def render_batch(self, scene, poses):
      gate.wait(30)
      _GateEngine.dispatches += 1
      return np.zeros((len(poses), 2, 2, 3), np.float32)

  mb = MicroBatcher(_GateEngine(), scene_provider=lambda sid: None,
                    max_batch=1, max_wait_ms=0.0, max_queue=2).start()
  try:
    first = mb.submit("s", _pose())      # taken by the dispatcher, gated
    for _ in range(100):                 # wait for the queue to drain to it
      if mb.metrics.snapshot()["queue_depth"] == 0:
        break
      time.sleep(0.01)
    backlog = [mb.submit("s", _pose()) for _ in range(2)]  # fills max_queue
    with pytest.raises(QueueFullError, match="queue full"):
      mb.submit("s", _pose())
    assert mb.rejected == 1
    gate.set()
    for fut in [first] + backlog:
      assert fut.result(30).shape == (2, 2, 3)
  finally:
    gate.set()
    mb.stop()


def test_cancelled_head_does_not_kill_dispatcher():
  """A cancelled request at the queue head must be dropped, not treated
  as the stop signal — requests behind it still get served."""
  import time

  from mpi_vision_tpu.serve.scheduler import MicroBatcher

  gate = threading.Event()

  class _GateEngine:
    def render_batch(self, scene, poses):
      gate.wait(30)
      return np.zeros((len(poses), 2, 2, 3), np.float32)

  mb = MicroBatcher(_GateEngine(), scene_provider=lambda sid: None,
                    max_batch=2, max_wait_ms=0.0, max_queue=8).start()
  try:
    first = mb.submit("scene_a", _pose())   # taken by the dispatcher, gated
    for _ in range(100):
      if mb.metrics.snapshot()["queue_depth"] == 0:
        break
      time.sleep(0.01)
    doomed = mb.submit("scene_b", _pose())  # next head once the gate opens
    live = mb.submit("scene_c", _pose())
    assert doomed.cancel()
    gate.set()
    assert live.result(30).shape == (2, 2, 3)
    assert first.result(30).shape == (2, 2, 3)
    assert mb._thread.is_alive()
  finally:
    gate.set()
    mb.stop()


def test_closed_service_rejects_submissions():
  service = RenderService(max_batch=2, max_wait_ms=1.0, use_mesh=False)
  service.add_synthetic_scenes(1, height=H, width=W, planes=P)
  service.close()
  with pytest.raises(RuntimeError, match="not running"):
    service.render_async("scene_000", _pose())


# --- HTTP front end ------------------------------------------------------


@pytest.fixture(scope="module")
def http_base(svc):
  httpd = make_http_server(svc, port=0)
  thread = threading.Thread(target=httpd.serve_forever, daemon=True)
  thread.start()
  yield f"http://127.0.0.1:{httpd.server_address[1]}"
  httpd.shutdown()


def _get_json(url):
  with urllib.request.urlopen(url, timeout=60) as resp:
    return json.load(resp)


def test_http_healthz(http_base):
  out = _get_json(http_base + "/healthz")
  assert out["status"] == "ok" and out["scenes"] == 2 and out["devices"] >= 1


def test_http_render_roundtrip_bitwise(svc, http_base):
  pose = _pose(0.015)
  body = json.dumps({"scene_id": "scene_000",
                     "pose": pose.tolist()}).encode()
  req = urllib.request.Request(http_base + "/render", data=body)
  with urllib.request.urlopen(req, timeout=120) as resp:
    out = json.load(resp)
  img = np.frombuffer(base64.b64decode(out["image_b64"]),
                      out["dtype"]).reshape(out["shape"])
  np.testing.assert_array_equal(img, svc.render("scene_000", pose))


def test_http_stats(http_base):
  stats = _get_json(http_base + "/stats")
  assert "latency_ms" in stats and "batch_size_hist" in stats
  assert "hit_rate" in stats["cache"]


def test_http_errors(http_base):
  pose = _pose().tolist()
  cases = [
      ("/render", {"scene_id": "nope", "pose": pose}, 404),
      ("/render", {"scene_id": "scene_000"}, 400),
      ("/render", {"scene_id": "scene_000", "pose": [[1.0]]}, 400),
      ("/wrong", {"scene_id": "scene_000", "pose": pose}, 404),
  ]
  for path, payload, want in cases:
    req = urllib.request.Request(http_base + path,
                                 data=json.dumps(payload).encode())
    with pytest.raises(urllib.error.HTTPError) as err:
      urllib.request.urlopen(req, timeout=60)
    assert err.value.code == want, (path, payload)


def test_http_rejects_nondict_body(http_base):
  req = urllib.request.Request(http_base + "/render", data=b"[1, 2, 3]")
  with pytest.raises(urllib.error.HTTPError) as err:
    urllib.request.urlopen(req, timeout=60)
  assert err.value.code == 400


def test_http_rejects_oversized_body(http_base):
  # The server 400s from the Content-Length header alone and closes; a
  # client mid-upload may see the reset (EPIPE) instead of the response —
  # both are the rejection, never an OOM-sized buffer.
  body = b'{"pad": "' + b" " * (1 << 20) + b'"}'
  req = urllib.request.Request(http_base + "/render", data=body)
  with pytest.raises((urllib.error.HTTPError, urllib.error.URLError)) as err:
    urllib.request.urlopen(req, timeout=60)
  if isinstance(err.value, urllib.error.HTTPError):
    assert err.value.code == 400
