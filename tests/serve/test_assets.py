"""Content-addressed scene-asset delivery (serve/assets) end to end.

The acceptance pins from the asset-tier issue live here:

  (1) **manifest schema stability** — the versioned manifest's key set,
      grid block, and digest matrix are pinned (clients cache against
      this contract);
  (2) **bit-identical assets** — the bytes served under a tile digest
      decode to exactly the baked crop bytes the digest was computed
      over (content addressing is meaningless otherwise);
  (3) **immutability across swaps** — after a partial ``swap_scenes``,
      unchanged tiles keep their digests, their asset URLs, and their
      strong ETags, and conditional GETs answer 304 THROUGH a real
      router in front of real HTTP backends;
  (4) **corrupt bake refused** — bytes that do not hash to their digest
      can never be published (counted reject), so a corrupt asset can
      never be cached forever downstream;
  (5) **tile-diff sync** — a cross-process ``SceneFetcher`` fetches
      EXACTLY the changed-digest tile set, verifies every transfer, and
      lands the diff atomically.

Scene geometry mirrors test_tiles.py: 16x16, 4 planes, tile 8 (a 2x2
grid) — every structure engages, every operation is toy-sized.
"""

import hashlib
import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from mpi_vision_tpu.serve import RenderService
from mpi_vision_tpu.serve import tiles as tiles_mod
from mpi_vision_tpu.serve.assets import (
    ASSET_CACHE_CONTROL,
    MANIFEST_VERSION,
    AssetIntegrityError,
    AssetStore,
    SceneFetcher,
    SceneSyncError,
    SceneSyncWatcher,
)
from mpi_vision_tpu.serve.assets import store as store_mod
from mpi_vision_tpu.serve.cluster.router import Router, make_router_http_server
from mpi_vision_tpu.serve.server import make_http_server, synthetic_tiled_scene

H = W = 16
P = 4
TILE = 8  # 2x2 grid


def _scene(seed=0):
  return synthetic_tiled_scene("s", height=H, width=W, planes=P,
                               regions=2, seed=seed)


def _mutate_tile00(layers):
  """A copy whose (0,0) tile — and ONLY that tile — has new bytes."""
  out = np.array(layers, copy=True)
  out[:TILE, :TILE] = (out[:TILE, :TILE] + 0.125) % 1.0
  return out


def _tiled_svc(layers, depths, k, **kwargs):
  svc = RenderService(max_batch=2, tile=kwargs.pop("tile", TILE), **kwargs)
  svc.add_scene("s", layers, depths, k)
  return svc


# -- auto tile sizing -------------------------------------------------------


def test_auto_tile_pins():
  # ~64 tiles, multiple of 8, floor 8, never larger than the scene.
  assert tiles_mod.auto_tile(256, 256) == 32
  assert tiles_mod.auto_tile(64, 64) == 8
  assert tiles_mod.auto_tile(16, 16) == 8
  assert tiles_mod.auto_tile(4, 4) == 4  # whole-scene single tile
  assert tiles_mod.auto_tile(512, 128) == 32  # non-square: sqrt(HW/64)
  with pytest.raises(ValueError, match="bad scene dims"):
    tiles_mod.auto_tile(0, 16)


def test_tile_size_auto_service_derives_per_scene_grid():
  layers, depths, k = _scene()
  svc = _tiled_svc(layers, depths, k, tile="auto")
  try:
    meta = svc.tile_meta("s")
    assert meta.grid.tile == tiles_mod.auto_tile(H, W) == 8
    # Same data under an explicit tile 8: identical digests — "auto"
    # is a sizing policy, not a different encoding.
    explicit = _tiled_svc(layers, depths, k, tile=8)
    try:
      assert meta.digests == explicit.tile_meta("s").digests
    finally:
      explicit.close()
  finally:
    svc.close()


def test_bad_tile_values_refused():
  with pytest.raises(ValueError, match="tile must be an int"):
    RenderService(tile="bogus")
  with pytest.raises(ValueError):
    RenderService(tile=4)


# -- manifest + asset contract (in-process) ---------------------------------


@pytest.fixture(scope="module")
def svc():
  layers, depths, k = _scene()
  service = _tiled_svc(layers, depths, k)
  yield service
  service.close()


def test_manifest_schema_pin(svc):
  man = svc.scene_manifest("s")
  assert set(man) == {
      "version", "scene_id", "scene_digest", "params_digest", "grid",
      "planes", "dtype", "depths", "intrinsics", "encoding", "tiles",
      "layers", "asset_path",
  }
  assert man["version"] == MANIFEST_VERSION
  assert man["grid"] == {"height": H, "width": W, "tile": TILE,
                         "rows": 2, "cols": 2}
  assert man["planes"] == P and man["dtype"] == "<f4"
  assert man["encoding"] == {"tiles": "raw-f32+zlib", "layers": "png"}
  assert man["asset_path"] == "/scene/s/asset/"
  meta = svc.tile_meta("s")
  assert man["scene_digest"] == meta.scene_digest
  assert man["tiles"] == [[meta.digests[i][j] for j in range(2)]
                          for i in range(2)]
  assert len(man["layers"]) == P
  # Cached per generation: the identical object until the scene changes.
  assert svc.scene_manifest("s") is man
  with pytest.raises(KeyError):
    svc.scene_manifest("nope")


def test_tile_asset_bytes_bit_identical_to_baked_crop(svc):
  man = svc.scene_manifest("s")
  entry = svc.scene_entry("s")
  meta = svc.tile_meta("s")
  for i in range(2):
    for j in range(2):
      digest = man["tiles"][i][j]
      encoded, serve_meta = svc.scene_asset("s", digest)
      assert serve_meta["kind"] == "tile"
      assert serve_meta["encoding"] == "raw-f32+zlib"
      raw = store_mod.decode_tile(encoded)
      y0, y1, x0, x1 = meta.grid.rect(i, j)
      expect = np.ascontiguousarray(entry[0][y0:y1, x0:x1]).tobytes()
      assert raw == expect
      assert hashlib.sha256(raw).hexdigest() == digest


def test_layer_assets_are_digest_addressed_pngs(svc):
  man = svc.scene_manifest("s")
  for digest in man["layers"]:
    body, serve_meta = svc.scene_asset("s", digest)
    assert serve_meta["kind"] == "layer"
    assert serve_meta["content_type"] == "image/png"
    assert body[:8] == b"\x89PNG\r\n\x1a\n"
    assert hashlib.sha256(body).hexdigest() == digest


def test_unknown_digest_is_a_key_error(svc):
  with pytest.raises(KeyError, match="unknown asset digest"):
    svc.scene_asset("s", "0" * 64)


def test_viewer_html_references_assets_not_base64(svc):
  html, scene_digest = svc.scene_viewer_html("s")
  assert scene_digest == svc.tile_meta("s").scene_digest
  man = svc.scene_manifest("s")
  for digest in man["layers"]:
    assert f"/scene/s/asset/{digest}" in html
  assert "base64" not in html


def test_evicted_asset_reencodes_bit_identically():
  # A byte budget too small for the scene: every request beyond the
  # first evicts, so later requests hit the re-encode path — which must
  # reproduce the digest's exact bytes (verified inside put()).
  layers, depths, k = _scene()
  small = _tiled_svc(layers, depths, k, asset_cache_bytes=1)
  try:
    man = small.scene_manifest("s")
    digests = [d for row in man["tiles"] for d in row]
    first = {d: small.scene_asset("s", d)[0] for d in digests}
    assert small.assets.stats()["evictions"] > 0
    again = {d: small.scene_asset("s", d)[0] for d in digests}
    assert first == again
  finally:
    small.close()


def test_corrupt_publish_refused():
  store = AssetStore()
  good = b"the real bytes"
  with pytest.raises(AssetIntegrityError, match="corrupt bake refused"):
    store.put(store_mod.digest_of(good), b"tampered bytes",
              b"tampered bytes", {"kind": "tile"})
  assert store.stats()["rejects"] == 1
  assert store.get(store_mod.digest_of(good)) is None  # nothing landed


def test_asset_metrics_and_stats_blocks(svc):
  snap = svc.metrics.snapshot()
  assert {"manifest_requests", "requests", "not_found", "not_modified",
          "bytes_served", "encodes",
          "publish_rejects"} <= set(snap["assets"])
  assert {"runs", "tiles_fetched", "tiles_reused", "bytes_fetched",
          "failures"} <= set(snap["scene_sync"])
  cache = svc.stats()["assets"]["cache"]
  assert cache["live_digests"] >= 4 and cache["byte_budget"] > 0


# -- tile-diff sync (socket-free) -------------------------------------------


class FakeTransport:
  """Serve a remote RenderService's asset surface in-process, recording
  every path — the sync tests pin EXACT fetch sets against it."""

  def __init__(self, remote):
    self.remote = remote
    self.paths = []
    self.tamper = None  # digest -> substitute body

  def get(self, url, headers=None):
    path = url[len("http://origin"):]
    self.paths.append(path)
    try:
      if path == "/scenes":
        return 200, {}, json.dumps(
            {"scenes": self.remote.scene_ids()}).encode()
      if path.endswith("/manifest"):
        sid = path.split("/")[2]
        man = self.remote.scene_manifest(sid)
        return 200, {}, json.dumps(man).encode()
      sid, digest = path.split("/")[2], path.split("/")[4]
      if self.tamper and digest in self.tamper:
        return 200, {}, self.tamper[digest]
      body, _ = self.remote.scene_asset(sid, digest)
      return 200, {}, body
    except KeyError:
      return 404, {}, b"{}"

  def asset_digests(self):
    return {p.split("/")[4] for p in self.paths if "/asset/" in p}


@pytest.fixture()
def origin():
  layers, depths, k = _scene()
  service = _tiled_svc(layers, depths, k)
  yield service, layers, depths, k
  service.close()


@pytest.fixture()
def replica():
  service = RenderService(max_batch=2, tile=TILE)
  yield service
  service.close()


def test_full_sync_then_in_sync(origin, replica):
  svc, layers, _, _ = origin
  transport = FakeTransport(svc)
  fetcher = SceneFetcher(replica, "http://origin", transport=transport)
  stats = fetcher.sync_scene("s")
  assert stats["tiles_fetched"] == 4 and stats["tiles_reused"] == 0
  assert stats["bytes_fetched"] > 0
  assert np.array_equal(replica.scene_entry("s")[0], layers)
  assert replica.tile_meta("s").scene_digest == svc.tile_meta("s").scene_digest
  again = fetcher.sync_scene("s")
  assert again["in_sync"] and again["tiles_fetched"] == 0
  snap = replica.metrics.snapshot()["scene_sync"]
  assert snap["runs"] == 2 and snap["tiles_fetched"] == 4


def test_diff_sync_fetches_exactly_the_changed_tile_set(origin, replica):
  svc, layers, depths, k = origin
  transport = FakeTransport(svc)
  fetcher = SceneFetcher(replica, "http://origin", transport=transport)
  fetcher.sync_scene("s")
  old_meta = svc.tile_meta("s")
  svc.swap_scenes({"s": (_mutate_tile00(layers), depths, k)})
  new_meta = svc.tile_meta("s")
  changed = {new_meta.digests[i][j]
             for (i, j) in old_meta.changed_tiles(new_meta)}
  assert len(changed) == 1  # only tile (0,0) has new bytes
  transport.paths.clear()
  stats = fetcher.sync_scene("s")
  assert stats["tiles_fetched"] == 1 and stats["tiles_reused"] == 3
  # THE pin: the wire saw exactly the changed-digest set, nothing else.
  assert transport.asset_digests() == changed
  assert np.array_equal(replica.scene_entry("s")[0],
                        svc.scene_entry("s")[0])


def test_corrupt_transfer_never_lands(origin, replica):
  svc, layers, _, _ = origin
  transport = FakeTransport(svc)
  fetcher = SceneFetcher(replica, "http://origin", transport=transport)
  fetcher.sync_scene("s")
  before = np.array(replica.scene_entry("s")[0], copy=True)
  digest = svc.scene_manifest("s")["tiles"][0][0]
  transport.tamper = {
      digest: store_mod.encode_tile(b"\x00" * (TILE * TILE * P * 4 * 4))}
  # Force a re-fetch of the tampered tile by clearing the local scene.
  replica2 = RenderService(max_batch=2, tile=TILE)
  try:
    fetcher2 = SceneFetcher(replica2, "http://origin", transport=transport)
    with pytest.raises(SceneSyncError, match="digest verification"):
      fetcher2.sync_scene("s")
    assert replica2.scene_entry("s") is None  # atomic: nothing landed
    assert replica2.metrics.snapshot()["scene_sync"]["failures"] == 1
  finally:
    replica2.close()
  assert np.array_equal(replica.scene_entry("s")[0], before)


def test_sync_all_counts_failures_and_converges_the_rest(origin, replica):
  svc, _, _, _ = origin
  # Distinct content: shared digests would let one tampered asset fail
  # BOTH scenes (content addressing dedups identical tiles).
  layers, depths, k = _scene(seed=9)
  svc.add_scene("t", layers, depths, k)
  transport = FakeTransport(svc)
  digest = svc.scene_manifest("t")["tiles"][1][1]
  transport.tamper = {digest: b"not even zlib"}
  fetcher = SceneFetcher(replica, "http://origin", transport=transport)
  sweep = fetcher.sync_all()
  assert sweep["scenes"] == 1 and sweep["failures"] == 1
  assert replica.scene_entry("s") is not None
  assert replica.scene_entry("t") is None


def test_scene_sync_watcher_counts_and_recovers(origin, replica):
  svc, _, _, _ = origin
  transport = FakeTransport(svc)
  fetcher = SceneFetcher(replica, "http://origin", transport=transport)
  watcher = SceneSyncWatcher(fetcher, poll_s=5.0)
  sweep = watcher.check_once()
  assert sweep["scenes"] == 1 and watcher.sync_errors == 0

  class DownTransport:
    def get(self, url, headers=None):
      raise ConnectionError("origin down")

  fetcher.transport = DownTransport()
  assert watcher.check_once() is None
  assert watcher.sync_errors == 1
  assert "origin down" in watcher.snapshot()["last_error"]
  fetcher.transport = transport  # outage ends; the next sweep converges
  assert watcher.check_once()["in_sync"] == 1
  snap = watcher.snapshot()
  assert snap["polls"] == 3 and snap["source"] == "http://origin"


def test_sync_events_emitted(origin, replica):
  svc, _, _, _ = origin
  fetcher = SceneFetcher(replica, "http://origin",
                         transport=FakeTransport(svc))
  fetcher.sync_scene("s")
  kinds = [e["kind"] for e in replica.events.snapshot(recent=16)["events"]]
  assert "scene_sync_begin" in kinds and "scene_sync_end" in kinds


# -- the real-HTTP / router acceptance pin ----------------------------------


@pytest.fixture(scope="module")
def fleet():
  """One scene-holding backend + one empty backend behind a real router
  — asset GETs must answer from whichever replica holds the digest."""
  layers, depths, k = _scene(seed=3)
  svc = _tiled_svc(layers, depths, k)
  empty = RenderService(max_batch=2, tile=TILE)
  servers = [make_http_server(svc, port=0), make_http_server(empty, port=0)]
  for server in servers:
    threading.Thread(target=server.serve_forever, daemon=True).start()
  router = Router()
  router.add_backend("holder", f"127.0.0.1:{servers[0].server_address[1]}")
  router.add_backend("empty", f"127.0.0.1:{servers[1].server_address[1]}")
  rsrv = make_router_http_server(router, port=0)
  threading.Thread(target=rsrv.serve_forever, daemon=True).start()
  base = f"http://127.0.0.1:{rsrv.server_address[1]}"
  yield svc, router, base, (layers, depths, k)
  rsrv.shutdown()
  for server in servers:
    server.shutdown()
  svc.close()
  empty.close()


def _get(base, path, etag=None):
  req = urllib.request.Request(base + path)
  if etag:
    req.add_header("If-None-Match", etag)
  try:
    with urllib.request.urlopen(req, timeout=10) as resp:
      return resp.status, dict(resp.headers), resp.read()
  except urllib.error.HTTPError as e:
    return e.code, dict(e.headers), e.read()


def test_unchanged_tiles_survive_partial_swap_through_router(fleet):
  svc, router, base, (layers, depths, k) = fleet
  status, headers, body = _get(base, "/scene/s/manifest")
  assert status == 200 and headers["Cache-Control"] == "no-cache"
  man = json.loads(body)
  unchanged = man["tiles"][1][1]  # tile (1,1): the swap won't touch it

  status, headers, body = _get(base, f"/scene/s/asset/{unchanged}")
  assert status == 200
  assert headers["Cache-Control"] == ASSET_CACHE_CONTROL
  etag = headers["ETag"]
  assert etag == f'"{unchanged}"'  # strong, content-derived
  assert hashlib.sha256(store_mod.decode_tile(body)).hexdigest() == unchanged

  svc.swap_scenes({"s": (_mutate_tile00(layers), depths, k)})

  status, _, body = _get(base, "/scene/s/manifest")
  man2 = json.loads(body)
  assert man2["scene_digest"] != man["scene_digest"]
  assert man2["tiles"][0][0] != man["tiles"][0][0]  # the changed tile
  assert man2["tiles"][1][1] == unchanged  # URL/digest stable across swap
  # THE pin: a conditional GET on the unchanged tile's ETag answers 304
  # through the real router — the client's immutable copy is still good.
  status, headers, body = _get(base, f"/scene/s/asset/{unchanged}", etag=etag)
  assert status == 304 and body == b""
  # And an unconditional re-fetch is byte-identical.
  status, headers, body = _get(base, f"/scene/s/asset/{unchanged}")
  assert status == 200 and headers["ETag"] == etag


def test_router_fans_asset_gets_past_404s(fleet):
  svc, router, base, _ = fleet
  digest = json.loads(_get(base, "/scene/s/manifest")[2])["tiles"][0][1]
  before = router.metrics.snapshot()["scene_sync"]
  # Whatever the placement order, the GET must land on the holder.
  status, headers, body = _get(base, f"/scene/s/asset/{digest}")
  assert status == 200 and headers["X-Backend-Id"] == "holder"
  status, _, _ = _get(base, f"/scene/s/asset/{'f' * 64}")
  assert status == 404
  after = router.metrics.snapshot()["scene_sync"]
  assert after["asset_misses"] == before["asset_misses"] + 1
  assert after["asset_forwards"] >= before["asset_forwards"] + 1


def test_scenes_union_and_sync_through_router(fleet):
  svc, router, base, _ = fleet
  status, _, body = _get(base, "/scenes")
  assert status == 200 and json.loads(body) == {"scenes": ["s"]}
  replica = RenderService(max_batch=2, tile=TILE)
  try:
    fetcher = SceneFetcher(replica, base)  # real HTTP, through the router
    sweep = fetcher.sync_all()
    assert sweep["scenes"] == 1 and sweep["failures"] == 0
    assert np.array_equal(replica.scene_entry("s")[0],
                          svc.scene_entry("s")[0])
  finally:
    replica.close()
