"""HTTP-layer fuzzing: hostile /render input maps to 4xx, never 500 or
dispatcher death (ROADMAP: "HTTP-layer fuzzing still open").

The contract under fuzz: for ANY malformed body, header, or framing the
server (1) answers 4xx with a JSON error and an X-Trace-Id, (2) keeps
the dispatcher thread alive, and (3) still serves a well-formed request
afterwards. Also pins the W3C ``traceparent`` satellite: a valid inbound
trace-id is echoed in ``X-Trace-Id`` (proxy trace stitching); invalid
ones are ignored, never rejected.
"""

import http.client
import json
import socket
import threading

import numpy as np
import pytest

from mpi_vision_tpu.serve import RenderService, make_http_server
from mpi_vision_tpu.serve.server import _inbound_trace_id


@pytest.fixture(scope="module")
def served():
  svc = RenderService(max_batch=2, max_wait_ms=0.5, resilience=None)
  svc.add_synthetic_scenes(1, height=16, width=16, planes=2)
  httpd = make_http_server(svc, port=0)
  thread = threading.Thread(target=httpd.serve_forever, daemon=True)
  thread.start()
  try:
    yield svc, httpd.server_address[1]
  finally:
    httpd.shutdown()
    svc.close()


def _post(port, body: bytes, headers=None, path="/render"):
  conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
  try:
    conn.request("POST", path, body=body,
                 headers={"Content-Type": "application/json",
                          **(headers or {})})
    resp = conn.getresponse()
    return resp.status, dict(resp.getheaders()), resp.read()
  finally:
    conn.close()


def _good_body():
  return json.dumps({"scene_id": "scene_000",
                     "pose": np.eye(4).tolist()}).encode()


BAD_BODIES = [
    b"",                                           # empty -> {} -> KeyError
    b"not json at all",
    b"{\"scene_id\": \"scene_000\"",               # truncated JSON
    b"[1, 2, 3]",                                  # not an object
    b"\"scene_000\"",                              # bare string
    b"{\"pose\": [[1]]}",                          # missing scene_id
    json.dumps({"scene_id": "scene_000"}).encode(),            # missing pose
    json.dumps({"scene_id": "scene_000", "pose": [[1, 2], [3, 4]]}).encode(),
    json.dumps({"scene_id": "scene_000", "pose": "eye"}).encode(),
    json.dumps({"scene_id": "scene_000",
                "pose": [["a"] * 4] * 4}).encode(),            # non-numeric
    json.dumps({"scene_id": {"nested": "dict"},
                "pose": np.eye(4).tolist()}).encode(),         # unhashable id
    json.dumps({"scene_id": None, "pose": np.eye(4).tolist()}).encode(),
    json.dumps({"scene_id": "scene_000",
                "pose": [[float("nan")] * 4] * 4}).encode(),   # non-finite
    # Control chars (esp. \x1f, the tile/ring key separator —
    # serve/tiles.py) must never reach the dispatcher as a scene id.
    json.dumps({"scene_id": "scene_000\x1ft0,0",
                "pose": np.eye(4).tolist()}).encode(),
    b"\xff\xfe garbage \x00\x01" * 16,             # binary junk
]


@pytest.mark.parametrize("body", BAD_BODIES,
                         ids=[f"body{i}" for i in range(len(BAD_BODIES))])
def test_malformed_bodies_map_to_400(served, body):
  svc, port = served
  status, headers, payload = _post(port, body)
  assert status == 400, payload
  assert "error" in json.loads(payload)
  assert headers.get("X-Trace-Id")
  assert svc.scheduler.dispatcher_alive()


def test_oversized_declared_length_is_4xx_without_buffering(served):
  svc, port = served
  # Declared 2 MB (over the 1 MB cap): must 4xx on the DECLARED length,
  # not block reading a body that never arrives.
  conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
  try:
    conn.putrequest("POST", "/render")
    conn.putheader("Content-Type", "application/json")
    conn.putheader("Content-Length", str(2 << 20))
    conn.endheaders()
    conn.send(b"{}")  # far fewer bytes than declared
    status = conn.getresponse().status
  finally:
    conn.close()
  assert status == 400
  assert svc.scheduler.dispatcher_alive()


def test_negative_content_length_is_4xx(served):
  svc, port = served
  # http.client refuses to send a negative length; raw socket it is.
  port_ = port
  with socket.create_connection(("127.0.0.1", port_), timeout=30) as sock:
    sock.sendall(b"POST /render HTTP/1.1\r\nHost: x\r\n"
                 b"Content-Length: -5\r\n\r\n")
    data = sock.recv(4096)
  assert b"400" in data.split(b"\r\n", 1)[0]
  assert svc.scheduler.dispatcher_alive()


def test_garbage_request_line_does_not_kill_server(served):
  svc, port = served
  with socket.create_connection(("127.0.0.1", port), timeout=30) as sock:
    sock.sendall(b"\x16\x03\x01\x02\x00 TLS-at-the-plain-port\r\n\r\n")
    sock.recv(4096)  # stdlib answers 400 (or closes); either is fine
  status, _, _ = _post(port, _good_body())
  assert status == 200
  assert svc.scheduler.dispatcher_alive()


def test_unknown_paths_are_404(served):
  svc, port = served
  status, _, _ = _post(port, _good_body(), path="/rendr")
  assert status == 404
  conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
  try:
    conn.request("GET", "/render")  # POST-only path via GET
    assert conn.getresponse().status == 404
  finally:
    conn.close()


def test_unknown_scene_is_404_not_500(served):
  svc, port = served
  body = json.dumps({"scene_id": "no_such_scene",
                     "pose": np.eye(4).tolist()}).encode()
  status, _, payload = _post(port, body)
  assert status == 404, payload
  assert svc.scheduler.dispatcher_alive()


def test_weird_accept_header_still_renders_json(served):
  svc, port = served
  status, headers, payload = _post(
      port, _good_body(),
      headers={"Accept": "text/html;q=0.9, image/avif, */*;q=0.8"})
  assert status == 200
  out = json.loads(payload)
  assert out["shape"] == [16, 16, 3]


def test_fuzz_then_valid_render_still_works(served):
  """After the whole hostile barrage the service still renders."""
  svc, port = served
  for body in BAD_BODIES[:4]:
    _post(port, body)
  status, headers, payload = _post(port, _good_body())
  assert status == 200
  out = json.loads(payload)
  assert out["scene_id"] == "scene_000" and out["dtype"] == "<f4"
  assert svc.scheduler.dispatcher_alive()
  assert svc.healthz()["status"] == "ok"


# -- W3C traceparent stitching (PR-4 satellite) ---------------------------

_VALID_TP = "00-4bf92f3577b34da6a3ce929d0e0e4736-00f067aa0ba902b7-01"


def test_traceparent_trace_id_is_honored(served):
  svc, port = served
  status, headers, _ = _post(port, _good_body(),
                             headers={"traceparent": _VALID_TP})
  assert status == 200
  assert headers["X-Trace-Id"] == _VALID_TP.split("-")[1]


def test_traceparent_honored_on_error_responses_too(served):
  svc, port = served
  status, headers, _ = _post(port, b"not json",
                             headers={"traceparent": _VALID_TP})
  assert status == 400
  assert headers["X-Trace-Id"] == _VALID_TP.split("-")[1]


@pytest.mark.parametrize("bad", [
    "",
    "banana",
    "00-" + "0" * 32 + "-00f067aa0ba902b7-01",      # all-zero trace id
    "00-4bf92f3577b34da6a3ce929d0e0e4736-" + "0" * 16 + "-01",  # zero parent
    "ff-4bf92f3577b34da6a3ce929d0e0e4736-00f067aa0ba902b7-01",  # version ff
    "00-4BF92F3577B34DA6A3CE929D0E0E4736-00f067aa0ba902b7-01",  # uppercase
    "00-4bf92f3577b34da6-00f067aa0ba902b7-01",      # short trace id
    _VALID_TP + "-extra",                           # version 00: no extras
])
def test_invalid_traceparent_is_ignored_not_rejected(served, bad):
  svc, port = served
  status, headers, _ = _post(port, _good_body(),
                             headers={"traceparent": bad})
  assert status == 200  # never reject a render over tracing garbage
  tid = headers["X-Trace-Id"]
  assert tid and tid != (bad.split("-")[1] if bad.count("-") >= 2 else bad)


def test_inbound_trace_id_parser_unit():
  assert _inbound_trace_id({"traceparent": _VALID_TP}) == _VALID_TP.split("-")[1]
  assert _inbound_trace_id({}) is None


def test_traceparent_future_version_with_extra_fields_is_honored():
  """W3C versioning: receivers parse versions > 00 by the version-00
  prefix, tolerating appended dash-separated fields — a proxy upgrade
  must not silently break trace stitching."""
  want = _VALID_TP.split("-")[1]
  future = "01-" + _VALID_TP[3:] + "-0badc0ffee"
  assert _inbound_trace_id({"traceparent": future}) == want
  # ... but version 00 is exactly four fields, and ff stays invalid.
  assert _inbound_trace_id({"traceparent": _VALID_TP + "-x"}) is None
  assert _inbound_trace_id(
      {"traceparent": "ff-" + _VALID_TP[3:] + "-x"}) is None


def test_traced_service_records_inbound_id(tmp_path):
  """With tracing on, the recorded trace carries the proxy's id."""
  from mpi_vision_tpu.obs import Tracer

  svc = RenderService(max_batch=2, max_wait_ms=0.5, resilience=None,
                      tracer=Tracer())
  svc.add_synthetic_scenes(1, height=16, width=16, planes=2)
  httpd = make_http_server(svc, port=0)
  threading.Thread(target=httpd.serve_forever, daemon=True).start()
  try:
    port = httpd.server_address[1]
    status, headers, _ = _post(port, _good_body(),
                               headers={"traceparent": _VALID_TP})
    assert status == 200
    want = _VALID_TP.split("-")[1]
    assert headers["X-Trace-Id"] == want
    recorded = [t["trace_id"] for t in svc.tracer.snapshot()["recent"]]
    assert want in recorded
  finally:
    httpd.shutdown()
    svc.close()
