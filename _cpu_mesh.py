"""Force JAX onto a virtual n-device CPU mesh — the single shared hardening.

Used by BOTH ``tests/conftest.py`` and ``__graft_entry__.dryrun_multichip``
so the two cannot drift: multi-chip TPU hardware is absent in CI, and the
standard JAX substitute is ``--xla_force_host_platform_device_count``
(SURVEY.md §4d). The ambient environment may point JAX at a tunnelled TPU
backend (axon) whose initialization can hang CPU-only runs even under
``JAX_PLATFORMS=cpu``, so hardening has two parts:

  1. env vars (must be in place before JAX builds its first backend);
  2. swapping the 'axon'/'tpu' backend factories for quietly-failing stubs —
     platform names stay *known* (Pallas' 'tpu' lowering registration needs
     that) but the tunnelled backend can never be constructed.

This module must stay importable without triggering a JAX import at module
scope (callers need to mutate env first).
"""

from __future__ import annotations

import os


def hardened_env(n_devices: int, base: dict | None = None) -> dict:
  """A copy of ``base`` (default ``os.environ``) forcing the CPU mesh."""
  env = dict(os.environ if base is None else base)
  flags = [f for f in env.get("XLA_FLAGS", "").split()
           if "xla_force_host_platform_device_count" not in f]
  flags.append(f"--xla_force_host_platform_device_count={n_devices}")
  env["XLA_FLAGS"] = " ".join(flags)
  env["JAX_PLATFORMS"] = "cpu"
  env.pop("PALLAS_AXON_POOL_IPS", None)
  return env


def force_cpu_mesh(n_devices: int = 8) -> None:
  """Apply the full hardening to THIS process (env + backend factories).

  Call before first device use; the env half only sticks if no JAX backend
  has been initialized yet in this process.
  """
  os.environ.update(hardened_env(n_devices))
  os.environ.pop("PALLAS_AXON_POOL_IPS", None)

  # Long test sessions (hundreds of XLA:CPU compilations in one process)
  # have segfaulted INSIDE LLVM on the main thread (rc=139 in
  # backend_compile_and_load, deterministic at ~40 min into the full
  # suite, absent from any half-suite run). The classic mechanism is
  # compiler recursion overrunning the default 8 MB main-thread stack —
  # Linux grows the main stack on fault up to RLIMIT_STACK, so raising
  # the soft limit early gives LLVM headroom without affecting anything
  # else. Harmless if the crash had another cause.
  try:
    import resource

    soft, hard = resource.getrlimit(resource.RLIMIT_STACK)
    want = 512 * 1024 * 1024
    if soft != resource.RLIM_INFINITY and soft < want:
      new_soft = want if hard == resource.RLIM_INFINITY else min(want, hard)
      resource.setrlimit(resource.RLIMIT_STACK, (new_soft, hard))
  except (ImportError, ValueError, OSError):
    pass

  # The ACTUAL culprit of the rc=139 crashes (measured by sampling
  # /proc/<pid>/maps during a full run): the process's memory-mapping
  # count climbs steadily — ~64k mappings after ~230 tests of jit
  # executables — and the kernel's default vm.max_map_count (65530) is
  # crossed right where the suite deterministically died; past the limit
  # every further mmap fails and the next executable materialization
  # (compile OR cache-load) segfaults. Raise the knob best-effort (needs
  # root, which this image has); skip silently otherwise — half-size
  # sessions fit under the default.
  try:
    with open("/proc/sys/vm/max_map_count") as fh:
      cur = int(fh.read().strip())
    if cur < 1048576:
      with open("/proc/sys/vm/max_map_count", "w") as fh:
        fh.write("1048576")
  except (OSError, ValueError):
    pass

  import jax
  import jax._src.xla_bridge as xb

  def _disabled(*args, **kwargs):
    raise RuntimeError("tpu/axon backends are disabled under the CPU mesh")

  for plat in ("axon", "tpu"):
    if plat in xb._backend_factories:
      xb.register_backend_factory(
          plat, _disabled, priority=-1000, fail_quietly=True)
  jax.config.update("jax_platforms", "cpu")

  # Persistent on-disk compilation cache (repo-local, gitignored). Two
  # reasons: (1) full-suite runs in ONE process segfault inside LLVM
  # after hundreds of XLA:CPU compilations (rc=139, deterministic,
  # ~40 min in; absent from half-suite runs; unaffected by the stack
  # raise above) — with the cache, a rerun loads the executables
  # compiled before any crash and performs a fraction of the native
  # compilations, sidestepping the accumulation; (2) iteration speed —
  # interpret-mode kernel tests dominate suite time with compiles.
  try:
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".jax_cache")
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
  except Exception:
    pass
